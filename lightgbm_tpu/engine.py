"""Training and cross-validation drivers (python-package/lightgbm/engine.py).

``train()`` mirrors engine.py:18-270: parameter normalization, callback
ordering (before/after iteration), early stopping via EarlyStopException,
evals_result recording.  ``cv()`` mirrors engine.py:375-580 with
group-aware / stratified / random folds and mean-stdv aggregation.
"""
from __future__ import annotations

import collections
import copy
import time
from typing import Any, Dict, List, Optional

import numpy as np

from . import callback, obs
from .basic import Booster, Dataset, LightGBMError
from .config import alias_transform
from .utils.log import Log
from .utils.timer import global_timer

__all__ = ["train", "cv", "serve", "serve_and_train", "CVBooster"]

_NUM_BOOST_ROUND_ALIASES = ("num_boost_round", "num_iterations", "num_iteration",
                            "n_iter", "num_tree", "num_trees", "num_round",
                            "num_rounds", "n_estimators")
_EARLY_STOP_ALIASES = ("early_stopping_round", "early_stopping_rounds",
                       "early_stopping", "n_iter_no_change")


def train(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          fobj=None, feval=None, init_model=None,
          feature_name="auto", categorical_feature="auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[Dict] = None, verbose_eval=True,
          learning_rates=None, keep_training_booster: bool = False,
          callbacks=None, checkpoint_prefix: Optional[str] = None,
          preemption_checkpoint: bool = False) -> Booster:
    """Train with given parameters; returns the trained Booster.

    ``checkpoint_prefix`` enables the fault-tolerant runtime: the full train
    state (model + RNG streams + score caches + early-stopping bookkeeping,
    lightgbm_tpu/checkpoint.py) is written atomically to
    ``<prefix>.ckpt_iter_<n>`` every ``snapshot_freq`` iterations (param;
    retention bounded by ``snapshot_keep``), and an interrupted run invoked
    again with the same prefix resumes bit-exactly from the newest valid
    checkpoint — corrupt/truncated files fall back to the previous good one.
    A call that completes removes its checkpoints (resume covers interrupted
    calls, not finished ones — continue a finished model via ``init_model``).
    Known limit: the ``early_stopping_rounds`` CALLBACK keeps its
    best-score/patience counters in a closure the checkpoint cannot reach,
    so they restart on resume (the resumed run may stop later than the
    uninterrupted one); the CLI / ``GBDT.train`` driver's internal
    early-stopping state rides the checkpoint and resumes bit-exactly.

    ``preemption_checkpoint=True`` (or the param of the same name) arms the
    SIGTERM/SIGINT preemption path: the handler sets a flag, the loop polls
    it at iteration boundaries, writes a leader-gated emergency checkpoint
    to ``checkpoint_prefix`` and raises
    :class:`~lightgbm_tpu.resilience.TrainingPreempted` — drivers convert
    that into exit code ``resilience.EXIT_PREEMPTED`` so a supervisor can
    tell resumable from failed.  ``watchdog_timeout_s > 0`` additionally
    arms the dispatch watchdog for the duration of the call.
    """
    params = copy.deepcopy(params) if params else {}
    for alias in _NUM_BOOST_ROUND_ALIASES:
        if alias in params:
            num_boost_round = int(params.pop(alias))
            Log.warning("Found `%s` in params. Will use it instead of argument",
                        alias)
    for alias in _EARLY_STOP_ALIASES:
        if alias in params:
            early_stopping_rounds = int(params.pop(alias))
            Log.warning("Found `%s` in params. Will use it instead of argument",
                        alias)
    first_metric_only = bool(params.get("first_metric_only", False))
    params.pop("first_metric_only", None)

    if fobj is not None:
        params["objective"] = "none"
    if num_boost_round <= 0:
        raise ValueError("num_boost_round should be greater than zero.")

    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature
    params["num_iterations"] = num_boost_round

    # round-18 kernel planner: engage the persisted tuned-plan cache (the
    # plan_cache param, or the default location next to the XLA cache)
    # BEFORE the Booster constructs its tree learner — the learner
    # resolves its dispatch plan at construction.  No cache present (the
    # default) means analytic plans, byte-equal to the hand-tuned
    # constants; an unusable cache degrades to analytic with one warning
    # and the plan_cache_fallbacks counter.
    from .plan import state as _plan_state
    _plan_state.configure(
        str(alias_transform(dict(params)).get("plan_cache", "") or "")
        or None)

    booster = Booster(params=params, train_set=train_set)
    if init_model is not None:
        if isinstance(init_model, str):
            with open(init_model) as fh:
                model_str = fh.read()
        elif isinstance(init_model, Booster):
            model_str = init_model.model_to_string()
        else:
            raise TypeError("init_model should be a path or a Booster")
        booster._booster.load_model_from_string(model_str)
        booster._booster.reset_training_data(train_set.handle,
                                             booster._booster.objective)
        # replay the loaded model onto the training scores in one blocked
        # binned pass (core/predict_fused.py) instead of per-tree dispatches
        booster._booster.replay_train_score()
    init_iteration = booster._booster.num_init_iteration

    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        if valid_names is None:
            valid_names = []
        elif isinstance(valid_names, str):
            valid_names = [valid_names]
        for i, vs in enumerate(valid_sets):
            if vs is train_set:
                continue
            name = valid_names[i] if i < len(valid_names) else "valid_%d" % i
            if vs.reference is None:
                vs.set_reference(train_set)
            booster.add_valid(vs, name)
    is_valid_contain_train = valid_sets is not None and any(
        vs is train_set for vs in (valid_sets or []))
    train_data_name = "training"
    if is_valid_contain_train and valid_names:
        idx = [i for i, vs in enumerate(valid_sets) if vs is train_set]
        if idx and idx[0] < len(valid_names):
            train_data_name = valid_names[idx[0]]

    resumed_iter = 0
    if checkpoint_prefix is not None:
        # restore AFTER the valid sets are attached: their score caches ride
        # the checkpoint and are restored positionally
        resumed_iter = booster._booster.resume_from_checkpoint(
            checkpoint_prefix)

    callbacks = set() if callbacks is None else set(callbacks)
    if verbose_eval is True:
        callbacks.add(callback.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval > 0:
        callbacks.add(callback.print_evaluation(verbose_eval))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.add(callback.early_stopping(
            early_stopping_rounds, first_metric_only,
            verbose=bool(verbose_eval)))
    if learning_rates is not None:
        callbacks.add(callback.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        callbacks.add(callback.record_evaluation(evals_result))

    callbacks_before_iter = {cb for cb in callbacks
                             if getattr(cb, "before_iteration", False)}
    callbacks_after_iter = callbacks - callbacks_before_iter
    callbacks_before_iter = sorted(callbacks_before_iter,
                                   key=lambda cb: getattr(cb, "order", 0))
    callbacks_after_iter = sorted(callbacks_after_iter,
                                  key=lambda cb: getattr(cb, "order", 0))

    # telemetry: a telemetry_out param turns this run self-recording (JSONL
    # events + <out>.summary.json); a run configured by the caller (bench.py)
    # is recorded into but finalized by its owner.  Under a pod every rank
    # records into its own <out>.rank<k>.jsonl shard (obs.configure picks
    # the path) and only the leader writes the merged summary at finalize;
    # metrics_port > 0 additionally serves the run live over HTTP
    # (obs/exporter.py), with an in-memory run when telemetry_out is unset.
    t_out = str(getattr(booster.config, "telemetry_out", "") or "")
    m_port = int(getattr(booster.config, "metrics_port", 0))
    from .parallel.learners import is_write_leader
    if t_out or m_port > 0:
        tele = obs.configure(
            out=t_out or None,
            freq=int(getattr(booster.config, "telemetry_freq", 1)),
            metrics_port=m_port,
            metrics_addr=str(getattr(booster.config, "metrics_addr", "")
                             or "127.0.0.1"),
            alert_rules=str(getattr(booster.config, "alert_rules", "")
                            or "") or None,
            alert_interval_s=float(getattr(booster.config,
                                           "alert_interval_s", 1.0)),
            flight_recorder=bool(getattr(booster.config,
                                         "flight_recorder", False)),
            entry="engine.train")
        own_tele = True
    else:
        tele = obs.active()
        own_tele = False
    t_start = time.perf_counter()

    # resilience supervision (lightgbm_tpu/resilience.py): SIGTERM/SIGINT
    # -> flag -> emergency checkpoint + TrainingPreempted; a watchdog
    # timeout arms the stalled-dispatch monitor for this call
    from . import resilience
    preempt = bool(preemption_checkpoint) or bool(
        getattr(booster.config, "preemption_checkpoint", False))
    if preempt and checkpoint_prefix is None:
        Log.warning("preemption_checkpoint is set without a "
                    "checkpoint_prefix: a preempted run exits cleanly "
                    "but has nothing to resume from")
    owned_handler, own_wd = resilience.arm_supervision(
        preempt, float(getattr(booster.config, "watchdog_timeout_s", 0.0)),
        artifact_base=t_out or checkpoint_prefix)

    try:
        ckpt_freq = int(getattr(booster.config, "snapshot_freq", -1))
        if checkpoint_prefix is not None:
            write_ckpt = is_write_leader(booster._booster.mesh)
            if ckpt_freq <= 0:
                Log.warning(
                    "checkpoint_prefix is set but snapshot_freq is not (<= 0): "
                    "no checkpoints will be written — pass snapshot_freq in "
                    "params to choose the cadence")
        else:
            write_ckpt = False
        # pre-assign: the loop body may never run (num_boost_round=0, or a
        # resume that restored the final iteration) yet the epilogue reads it
        evaluation_result_list = []
        for i in range(init_iteration + resumed_iter,
                       init_iteration + num_boost_round):
            for cb in callbacks_before_iter:
                cb(callback.CallbackEnv(model=booster, params=params, iteration=i,
                                        begin_iteration=init_iteration,
                                        end_iteration=init_iteration + num_boost_round,
                                        evaluation_result_list=None))
            it_t0 = time.perf_counter() if tele is not None else 0.0
            finished = booster.update(fobj=fobj)
            if tele is not None and (i + 1 - init_iteration) % tele.freq == 0:
                dt_it = time.perf_counter() - it_t0
                n_rows = int(booster._booster.num_data)
                tele.histogram("iteration_dispatch_s").observe(dt_it)
                tele.histogram("chunk_rows_per_s").observe(
                    n_rows / dt_it if dt_it > 0 else 0.0)
                tele.event("iteration", iteration=int(i), dt_s=dt_it,
                           rows_per_s=(n_rows / dt_it if dt_it > 0 else 0.0))
            evaluation_result_list = []
            if valid_sets is not None or booster._booster.train_metrics:
                if is_valid_contain_train:
                    evaluation_result_list.extend(
                        [(train_data_name, m, v, h)
                         for (_, m, v, h) in booster.eval_train(feval)])
                evaluation_result_list.extend(booster.eval_valid(feval))
            try:
                for cb in callbacks_after_iter:
                    cb(callback.CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=init_iteration,
                        end_iteration=init_iteration + num_boost_round,
                        evaluation_result_list=evaluation_result_list))
            except callback.EarlyStopException as earlyStopException:
                booster.best_iteration = earlyStopException.best_iteration + 1
                evaluation_result_list = earlyStopException.best_score
                break
            if (write_ckpt and ckpt_freq > 0
                    and booster._booster.iter_ % ckpt_freq == 0):
                # best-effort like every periodic durability write: a
                # disk-full checkpoint skip must not kill a healthy run
                from .checkpoint import save_checkpoint_best_effort
                save_checkpoint_best_effort(booster._booster,
                                            checkpoint_prefix)
            if preempt and resilience.preemption_requested():
                # ONE preempt-exit sequence for every driver: drain
                # in-flight device work, emergency checkpoint, consume the
                # flag, raise TrainingPreempted
                booster._booster._preempt_exit(checkpoint_prefix)
            if finished:
                break
        # the trailing < _poll_freq iterations' isfinite reductions
        # (nan_policy=raise) are only fetched by _poll_stop; drain them here so
        # a bad batch near the end still raises instead of returning NaN trees
        booster._booster._drain_nonfinite_checks()
        if write_ckpt:
            # this call COMPLETED (ran its rounds or stopped early): drop its
            # checkpoints so a rerun with the same prefix trains instead of
            # silently returning the finished run's model.  An interrupted call
            # never reaches this line — its checkpoints survive for the resume.
            from .checkpoint import cleanup_checkpoints
            cleanup_checkpoints(checkpoint_prefix)
        booster.best_score = collections.defaultdict(collections.OrderedDict)
        for data_name, eval_name, e_val, _ in (evaluation_result_list or []):
            booster.best_score[data_name][eval_name] = e_val
        if booster.best_iteration <= 0:
            booster.best_iteration = booster.current_iteration()
        if tele is not None:
            wall = time.perf_counter() - t_start
            b = booster._booster
            # iterations trained by THIS call (a checkpoint resume restored
            # `resumed_iter` of them before the loop; the wall covers only the
            # post-restore work, so must the iter count)
            iters_run = int(b.iter_) - int(resumed_iter)
            tele.gauge("train_rows").set(int(b.num_data))
            tele.gauge("train_iterations").set(iters_run)
            tele.gauge("train_wall_s").set(wall)
            if own_tele:
                from .obs.report import finalize_run
                finalize_run(tele, gbdt=b, wall_s=wall, iters=iters_run)
                # this call OWNS the run: close it so a later train() in the
                # same process (refits, CV loops, notebooks) doesn't append
                # events past run_end or clobber the headline gauges
                obs.disable()
        # reference exit-time dump at the end of the training driver too
        # (Log.debug-gated on verbosity)
        global_timer.print()
        return booster
    finally:
        resilience.disarm_supervision(owned_handler, own_wd)
        # exception path (nan_policy=raise, user fobj/callback
        # errors): the owned run must not stay process-active —
        # close it so a later train() cannot leak into the artifact
        if own_tele and obs.active() is tele:
            obs.disable()



def _configure_owned_telemetry(cfg, entry: str):
    """Serving-entry telemetry bootstrap shared by :func:`serve` and
    :func:`serve_and_train`: when the params ask for a run
    (``telemetry_out`` and/or ``metrics_port``) and none is active,
    configure one owned by the caller (its Server finalizes + closes it).
    Returns the Telemetry or None."""
    t_out = str(getattr(cfg, "telemetry_out", "") or "")
    m_port = int(getattr(cfg, "metrics_port", 0))
    if not (t_out or m_port > 0) or obs.active() is not None:
        return None
    # metrics_port without telemetry_out still gets a (memory-sink) run:
    # the live scrape surface needs a registry to render
    return obs.configure(out=t_out or None,
                         freq=int(getattr(cfg, "telemetry_freq", 1)),
                         metrics_port=m_port,
                         metrics_addr=str(getattr(cfg, "metrics_addr", "")
                                          or "127.0.0.1"),
                         alert_rules=str(getattr(cfg, "alert_rules", "")
                                         or "") or None,
                         alert_interval_s=float(
                             getattr(cfg, "alert_interval_s", 1.0)),
                         flight_recorder=bool(
                             getattr(cfg, "flight_recorder", False)),
                         entry=entry)


def serve(models, params: Optional[Dict[str, Any]] = None, **server_kwargs):
    """Start a serving tier (lightgbm_tpu/serving) over one or many models.

    ``models`` is a Booster / GBDT / model-file path, or a dict of
    ``name -> one of those`` for multi-model residency.  ``params`` feeds
    the serving knobs (``max_batch_wait_us``, ``serve_residency_budget_mb``,
    ``serve_single_row_fast``, plus ``telemetry_out`` if the caller has not
    configured a run); extra keyword arguments go to
    :class:`~lightgbm_tpu.serving.Server` (e.g. ``max_queue_depth``).
    Returns the running :class:`~lightgbm_tpu.serving.Server` — submit with
    ``server.submit(name, rows)`` / ``server.predict``, republish with
    ``server.swap``, and ``server.close()`` when done (also a context
    manager)."""
    from .config import Config
    from .serving import Server

    cfg = Config(alias_transform(dict(params or {})))
    own_tele = _configure_owned_telemetry(cfg, "engine.serve")
    # tuned-plan cache (round 18): engaged before any predictor stacks so
    # the warmup compiles under the plan the run will serve with
    from .plan import state as _plan_state
    _plan_state.configure_from_config(cfg)
    server = None
    try:
        # the run stays open for telemetry_summary() reads while serving;
        # server.close() finalizes it into <telemetry_out>.summary.json and
        # releases the process-active slot (same ownership rule as
        # engine.train)
        server = Server(config=cfg, owned_telemetry=own_tele,
                        **server_kwargs)
        if not isinstance(models, dict):
            models = {"model": models}
        for name, model in models.items():
            if isinstance(model, str):
                from .boosting.gbdt import GBDT
                model = GBDT.load_model(model, cfg)
            server.register(name, model)
    except BaseException:
        # a failed construction/load/register must not leak the dispatcher
        # thread or hold the process-active telemetry slot hostage (no
        # summary is finalized for a run that never served)
        if server is not None:
            server.disown_telemetry()
            server.close(drain=False)
        if own_tele is not None and obs.active() is own_tele:
            obs.disable()
        raise
    return server


def serve_and_train(booster, train_set=None,
                    params: Optional[Dict[str, Any]] = None,
                    name: str = "model",
                    checkpoint_prefix: Optional[str] = None,
                    publish_out: Optional[str] = None,
                    warm=True, **server_kwargs):
    """Start the train-while-serve loop (lightgbm_tpu/online): one process
    that serves ``booster`` through the round-13 tier while a trainer
    thread ingests fresh labeled rows (``controller.ingest(X, y)``) and
    republishes each continued generation through ``ModelRegistry.swap``.

    ``booster`` is a Booster / GBDT / model-file path; ``train_set`` the
    base :class:`~lightgbm_tpu.io.dataset.BinnedDataset` (or
    :class:`Dataset`) whose bin layout every ingested window is binned
    against (defaults to the booster's attached training data).
    ``params`` feeds both the serving knobs and the ``online_*`` policy
    params (cadence ``online_min_rows``/``online_interval_s``, the drift
    trigger, the freshness SLO, ``online_rounds``/``online_update``);
    ``checkpoint_prefix`` arms the steady-state checkpoint path (cycle
    windows + snapshot/emergency checkpoints land under it, and a rerun
    resumes the preempted cycle), ``publish_out`` persists each published
    generation's model text so a restarted process warm-starts from the
    newest one.  Extra keyword arguments go to
    :class:`~lightgbm_tpu.serving.Server`.

    Returns the running
    :class:`~lightgbm_tpu.online.OnlineController` — submit with
    ``controller.submit(rows)``, feed with ``controller.ingest(X, y)``,
    and ``controller.close()`` when done (also a context manager)."""
    from .config import Config
    from .online import OnlineController
    from .serving import Server

    cfg = Config(alias_transform(dict(params or {})))
    own_tele = _configure_owned_telemetry(cfg, "engine.serve_and_train")
    from .plan import state as _plan_state
    _plan_state.configure_from_config(cfg)
    server = None
    try:
        server = Server(config=cfg, owned_telemetry=own_tele,
                        **server_kwargs)
        if isinstance(booster, str):
            from .boosting.gbdt import GBDT
            booster = GBDT.load_model(booster, cfg)
        if train_set is not None:
            construct = getattr(train_set, "construct", None)
            if construct is not None:
                train_set = construct()
            train_set = getattr(train_set, "handle", train_set)
        controller = OnlineController(
            server=server, name=name, booster=booster, base_ds=train_set,
            config=cfg, checkpoint_prefix=checkpoint_prefix,
            publish_out=publish_out, warm=warm)
        controller.start()
    except BaseException:
        # a failed construction must not leak the dispatcher thread or
        # hold the process-active telemetry slot hostage (same unwind as
        # engine.serve)
        if server is not None:
            server.disown_telemetry()
            server.close(drain=False)
        if own_tele is not None and obs.active() is own_tele:
            obs.disable()
        raise
    return controller


class CVBooster:
    """Ensemble of per-fold boosters (engine.py:277 _CVBooster)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def _append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler_function(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold, params, seed,
                  fpreproc=None, stratified=True, shuffle=True,
                  eval_train_metric=False):
    full_data = full_data.construct()
    num_data = full_data.num_data()
    if folds is not None:
        if not hasattr(folds, "__iter__") and not hasattr(folds, "split"):
            raise AttributeError("folds should be a generator or iterator of "
                                 "(train_idx, test_idx) tuples or scikit-learn "
                                 "splitter object with split method")
        if hasattr(folds, "split"):
            group_info = full_data.get_group()
            if group_info is not None:
                group_info = np.asarray(group_info, dtype=np.int32)
                flatted_group = np.repeat(range(len(group_info)),
                                          repeats=group_info)
            else:
                flatted_group = np.zeros(num_data, dtype=np.int32)
            folds = folds.split(X=np.empty(num_data),
                                y=full_data.get_label(),
                                groups=flatted_group)
    else:
        if any(params.get(name) in {"lambdarank", "rank_xendcg"}
               for name in ("objective", "application")):
            # group-aware fold split (engine.py:313)
            group_info = np.asarray(full_data.get_group(), dtype=np.int32)
            num_group = len(group_info)
            group_kfold = _LGBMGroupKFold(n_splits=nfold)
            flatted_group = np.repeat(range(num_group), repeats=group_info)
            folds = group_kfold.split(np.empty(num_data), groups=flatted_group)
        elif stratified:
            labels = np.asarray(full_data.get_label())
            order = np.argsort(labels, kind="stable")
            folds_idx = [order[i::nfold] for i in range(nfold)]
            folds = [(np.setdiff1d(np.arange(num_data), fi), np.sort(fi))
                     for fi in folds_idx]
        else:
            if shuffle:
                randidx = np.random.RandomState(seed).permutation(num_data)
            else:
                randidx = np.arange(num_data)
            kstep = int(num_data / nfold)
            test_id = [randidx[i:i + kstep] for i in range(0, num_data, kstep)
                       ][:nfold]
            folds = [(np.setdiff1d(randidx, ti), np.sort(ti)) for ti in test_id]

    ret = CVBooster()
    for train_idx, test_idx in folds:
        train_subset = full_data.subset(sorted(train_idx))
        valid_subset = full_data.subset(sorted(test_idx))
        if fpreproc is not None:
            train_subset, valid_subset, tparam = fpreproc(
                train_subset, valid_subset, params.copy())
        else:
            tparam = params
        cvbooster = Booster(tparam, train_subset)
        if eval_train_metric:
            cvbooster.add_valid(train_subset, "train")
        cvbooster.add_valid(valid_subset, "valid")
        ret._append(cvbooster)
    return ret


class _LGBMGroupKFold:
    """Minimal GroupKFold (sklearn-compatible subset) for ranking cv."""

    def __init__(self, n_splits=5):
        self.n_splits = n_splits

    def split(self, X, y=None, groups=None):
        groups = np.asarray(groups)
        unique = np.unique(groups)
        for i in range(self.n_splits):
            test_groups = unique[i::self.n_splits]
            test_mask = np.isin(groups, test_groups)
            yield np.where(~test_mask)[0], np.where(test_mask)[0]


def _agg_cv_result(raw_results, eval_train_metric=False):
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            if eval_train_metric:
                key = "%s %s" % (one_line[0], one_line[1])
            else:
                key = one_line[1]
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, np.mean(v), metric_type[k], np.std(v))
            for k, v in cvmap.items()]


def cv(params, train_set, num_boost_round=100, folds=None, nfold=5,
       stratified=True, shuffle=True, metrics=None, fobj=None, feval=None,
       init_model=None, feature_name="auto", categorical_feature="auto",
       early_stopping_rounds=None, fpreproc=None, verbose_eval=None,
       show_stdv=True, seed=0, callbacks=None, eval_train_metric=False,
       return_cvbooster=False):
    """Cross-validation; returns dict of 'metric-mean'/'metric-stdv' lists."""
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    params = copy.deepcopy(params) if params else {}
    for alias in _NUM_BOOST_ROUND_ALIASES:
        if alias in params:
            num_boost_round = int(params.pop(alias))
    for alias in _EARLY_STOP_ALIASES:
        if alias in params:
            early_stopping_rounds = int(params.pop(alias))
    first_metric_only = bool(params.pop("first_metric_only", False))
    if fobj is not None:
        params["objective"] = "none"
    if metrics is not None:
        params["metric"] = metrics
    params["num_iterations"] = num_boost_round
    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    results = collections.defaultdict(list)
    cvfolds = _make_n_folds(train_set, folds=folds, nfold=nfold,
                            params=params, seed=seed, fpreproc=fpreproc,
                            stratified=stratified, shuffle=shuffle,
                            eval_train_metric=eval_train_metric)

    callbacks = set() if callbacks is None else set(callbacks)
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.add(callback.early_stopping(early_stopping_rounds,
                                              first_metric_only, verbose=False))
    if verbose_eval is True:
        callbacks.add(callback.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and verbose_eval > 0:
        callbacks.add(callback.print_evaluation(verbose_eval, show_stdv))
    callbacks_before_iter = sorted(
        (cb for cb in callbacks if getattr(cb, "before_iteration", False)),
        key=lambda cb: getattr(cb, "order", 0))
    callbacks_after_iter = sorted(
        (cb for cb in callbacks if not getattr(cb, "before_iteration", False)),
        key=lambda cb: getattr(cb, "order", 0))

    for i in range(num_boost_round):
        for cb in callbacks_before_iter:
            cb(callback.CallbackEnv(model=cvfolds, params=params, iteration=i,
                                    begin_iteration=0,
                                    end_iteration=num_boost_round,
                                    evaluation_result_list=None))
        for b in cvfolds.boosters:
            b.update(fobj=fobj)
        res = _agg_cv_result([b.eval_valid(feval) for b in cvfolds.boosters],
                             eval_train_metric)
        for _, key, mean, _, std in res:
            results[key + "-mean"].append(mean)
            results[key + "-stdv"].append(std)
        try:
            for cb in callbacks_after_iter:
                cb(callback.CallbackEnv(model=cvfolds, params=params,
                                        iteration=i, begin_iteration=0,
                                        end_iteration=num_boost_round,
                                        evaluation_result_list=res))
        except callback.EarlyStopException as earlyStopException:
            cvfolds.best_iteration = earlyStopException.best_iteration + 1
            for k in results:
                results[k] = results[k][:cvfolds.best_iteration]
            break
    if return_cvbooster:
        results["cvbooster"] = cvfolds
    return dict(results)
