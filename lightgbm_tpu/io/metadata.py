"""Per-row training metadata: labels, weights, query boundaries, init scores.

Counterpart of the reference ``Metadata`` (include/LightGBM/dataset.h:41-250,
src/io/metadata.cpp): owns label/weight/group/init_score arrays, converts per-row
query ids into query boundaries, and derives query weights when both weights and
queries are present.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..utils.log import Log


class Metadata:
    def __init__(self, num_data: int) -> None:
        self.num_data = int(num_data)
        self.label: np.ndarray = np.zeros(num_data, dtype=np.float32)
        self.weights: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None
        self.query_weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label) -> None:
        label = np.ascontiguousarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            Log.fatal("Length of label (%d) is not same with #data (%d)",
                      len(label), self.num_data)
        self.label = label

    def set_weights(self, weights) -> None:
        if weights is None:
            self.weights = None
            return
        weights = np.ascontiguousarray(weights, dtype=np.float32).reshape(-1)
        if len(weights) != self.num_data:
            Log.fatal("Length of weights (%d) is not same with #data (%d)",
                      len(weights), self.num_data)
        self.weights = weights
        self._update_query_weights()

    def set_group(self, group) -> None:
        """``group`` is per-query sizes (Python API convention, metadata.cpp SetQuery)."""
        if group is None:
            self.query_boundaries = None
            self.query_weights = None
            return
        sizes = np.ascontiguousarray(group, dtype=np.int64).reshape(-1)
        bounds = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
        if bounds[-1] != self.num_data:
            Log.fatal("Sum of query counts (%d) differs from #data (%d)",
                      bounds[-1], self.num_data)
        self.query_boundaries = bounds
        self._update_query_weights()

    def set_query_ids(self, qids) -> None:
        """Per-row query ids (CLI query-file convention) -> run-length sizes."""
        qids = np.ascontiguousarray(qids).reshape(-1)
        if len(qids) != self.num_data:
            Log.fatal("Length of query ids (%d) is not same with #data (%d)",
                      len(qids), self.num_data)
        change = np.flatnonzero(qids[1:] != qids[:-1]) + 1
        sizes = np.diff(np.concatenate([[0], change, [len(qids)]]))
        self.set_group(sizes)

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        init_score = np.ascontiguousarray(init_score, dtype=np.float64).reshape(-1)
        if len(init_score) % self.num_data != 0:
            Log.fatal("Initial score size (%d) is not a multiple of #data (%d)",
                      len(init_score), self.num_data)
        self.init_score = init_score

    def _update_query_weights(self) -> None:
        """Average row weight per query (metadata.cpp query weight derivation)."""
        if self.weights is None or self.query_boundaries is None:
            self.query_weights = None
            return
        b = self.query_boundaries
        sums = np.add.reduceat(self.weights, b[:-1])
        self.query_weights = (sums / np.maximum(np.diff(b), 1)).astype(np.float32)

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1

    def subset(self, indices: np.ndarray) -> "Metadata":
        out = Metadata(len(indices))
        out.label = self.label[indices]
        if self.weights is not None:
            out.weights = self.weights[indices]
        if self.init_score is not None:
            k = len(self.init_score) // self.num_data
            mat = self.init_score.reshape(k, self.num_data)
            out.init_score = mat[:, indices].reshape(-1)
        if self.query_boundaries is not None:
            # subsetting ranked data keeps whole queries only if indices align;
            # mirror the reference by re-deriving query ids per row
            qid = np.searchsorted(self.query_boundaries, indices, side="right") - 1
            out.set_query_ids(qid)
        out._update_query_weights()
        return out
