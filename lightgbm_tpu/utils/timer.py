"""Name-keyed wall-clock aggregation for host-side profiling.

Counterpart of the reference's ``Common::Timer``/``FunctionTimer``/``global_timer``
(include/LightGBM/utils/common.h:1032-1093): hot host paths are instrumented with
RAII-style scopes whose accumulated times are printed at process exit (and at
the end of ``engine.train``) when verbosity reaches debug, matching the
reference's exit-time dump.  Device-side profiling is jax.profiler's job; this
covers the host orchestration only.

Scopes STACK: nested/overlapping ``start(name)`` on the same key no longer
drops the outer scope — each ``stop`` closes the most recent open scope of
that name (per thread), so re-entrant instrumentation (a timed function
calling itself, or two threads sharing ``global_timer``) accumulates every
scope's elapsed time.  Start stacks are thread-local; the totals map is
lock-protected.
"""
from __future__ import annotations

import atexit
import threading
import time
from collections import OrderedDict
from contextlib import ContextDecorator
from typing import Dict, List


class Timer:
    def __init__(self) -> None:
        self._local = threading.local()
        self._lock = threading.Lock()
        self._totals: "OrderedDict[str, float]" = OrderedDict()
        # bumped by reset(): start stacks are thread-local, so reset cannot
        # reach another thread's in-flight scope — instead each scope
        # records the epoch it opened in and stop() discards scopes that
        # straddle a reset
        self._epoch = 0

    def _starts(self) -> Dict[str, List[tuple]]:
        starts = getattr(self._local, "starts", None)
        if starts is None:
            starts = self._local.starts = {}
        return starts

    def start(self, name: str) -> None:
        self._starts().setdefault(name, []).append(
            (self._epoch, time.perf_counter()))

    def stop(self, name: str) -> None:
        stack = self._starts().get(name)
        if stack:
            epoch, t0 = stack.pop()
            dt = time.perf_counter() - t0
            with self._lock:
                # epoch compared under the SAME lock reset() bumps it in:
                # a scope straddling a concurrent reset is discarded, not
                # added to the freshly-zeroed totals
                if epoch != self._epoch:
                    return
                self._totals[name] = self._totals.get(name, 0.0) + dt

    def total(self, name: str) -> float:
        with self._lock:
            return self._totals.get(name, 0.0)

    def totals(self) -> Dict[str, float]:
        """Snapshot of all accumulated scope totals (seconds)."""
        with self._lock:
            return dict(self._totals)

    def reset(self) -> None:
        self._starts().clear()
        with self._lock:
            self._totals.clear()
            self._epoch += 1

    def summary(self) -> str:
        lines = ["LightGBM-TPU host timing summary:"]
        for name, tot in sorted(self.totals().items(), key=lambda kv: -kv[1]):
            lines.append("  %s: %.6f s" % (name, tot))
        return "\n".join(lines)

    def print(self) -> None:
        from .log import Log
        Log.debug("%s", self.summary())


global_timer = Timer()


@atexit.register
def _print_at_exit() -> None:
    """The reference dumps global_timer when the process ends
    (common.h:1089-1093 ~Timer); Log.debug keeps it gated on
    verbosity >= debug like every other debug line."""
    if global_timer.totals():
        global_timer.print()


class FunctionTimer(ContextDecorator):
    """``with FunctionTimer("name"):`` or ``@FunctionTimer("name")`` scope timer."""

    def __init__(self, name: str, timer: Timer = global_timer) -> None:
        self._name = name
        self._timer = timer

    def __enter__(self) -> "FunctionTimer":
        self._timer.start(self._name)
        return self

    def __exit__(self, *exc) -> bool:
        self._timer.stop(self._name)
        return False
