"""Training callbacks.

The callback PROTOCOL is the reference's public contract
(python-package/lightgbm/callback.py): ``train``/``cv`` call each callback
with a ``CallbackEnv``, callbacks are ordered by an ``order`` attribute and
may set ``before_iteration``, and early stopping ends training by raising
``EarlyStopException``.  The implementations here are class-based: each
callback is a small object whose ``__call__`` takes the env, which keeps
per-callback state on the instance instead of in closures.
"""
from __future__ import annotations

import collections
from typing import Dict, List, Optional

from .utils.log import Log


class EarlyStopException(Exception):
    """Raised by the early-stopping callback to end training."""

    def __init__(self, best_iteration, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


def _format_eval_result(value, show_stdv=True):
    """(data_name, eval_name, value, bigger_better[, stdv]) -> log text."""
    if len(value) == 4:
        return "%s's %s: %g" % (value[0], value[1], value[2])
    if len(value) == 5:
        if show_stdv:
            return "%s's %s: %g + %g" % (value[0], value[1], value[2], value[4])
        return "%s's %s: %g" % (value[0], value[1], value[2])
    raise ValueError("Wrong metric value")


class _PrintEvaluation:
    order = 10
    before_iteration = False

    def __init__(self, period: int, show_stdv: bool) -> None:
        self.period = period
        self.show_stdv = show_stdv

    def __call__(self, env: CallbackEnv) -> None:
        if self.period <= 0 or not env.evaluation_result_list:
            return
        if (env.iteration + 1) % self.period:
            return
        line = "\t".join(_format_eval_result(v, self.show_stdv)
                         for v in env.evaluation_result_list)
        Log.info("[%d]\t%s", env.iteration + 1, line)


def print_evaluation(period=1, show_stdv=True):
    """Log evaluation results every ``period`` iterations."""
    return _PrintEvaluation(period, show_stdv)


class _RecordEvaluation:
    order = 20
    before_iteration = False

    def __init__(self, eval_result: dict) -> None:
        if not isinstance(eval_result, dict):
            raise TypeError("eval_result should be a dictionary")
        eval_result.clear()
        self.eval_result = eval_result

    def __call__(self, env: CallbackEnv) -> None:
        for entry in env.evaluation_result_list:
            data_name, eval_name, value = entry[0], entry[1], entry[2]
            per_data = self.eval_result.setdefault(
                data_name, collections.OrderedDict())
            per_data.setdefault(eval_name, []).append(value)


def record_evaluation(eval_result: dict):
    """Record evaluation history into ``eval_result``."""
    return _RecordEvaluation(eval_result)


class _ResetParameter:
    order = 10
    before_iteration = True

    def __init__(self, schedules: dict) -> None:
        self.schedules = schedules

    def _value_at(self, key, schedule, env: CallbackEnv):
        step = env.iteration - env.begin_iteration
        if isinstance(schedule, list):
            if len(schedule) != env.end_iteration - env.begin_iteration:
                raise ValueError(
                    "Length of list %r has to equal to 'num_boost_round'."
                    % key)
            return schedule[step]
        return schedule(step)

    def __call__(self, env: CallbackEnv) -> None:
        changed = {k: v for k, v in
                   ((key, self._value_at(key, sched, env))
                    for key, sched in self.schedules.items())
                   if env.params.get(k) != v}
        if changed:
            env.model.reset_parameter(changed)
            env.params.update(changed)


def reset_parameter(**kwargs):
    """Reset parameters on a schedule: a per-iteration value list or a
    ``callable(iteration) -> value`` per parameter name."""
    return _ResetParameter(kwargs)


class _MetricTracker:
    """Best-so-far state for one (dataset, metric) column."""

    def __init__(self, bigger_better: bool) -> None:
        self.sign = 1.0 if bigger_better else -1.0
        self.best = float("-inf")
        self.best_iteration = 0
        self.best_entries: Optional[List] = None

    def update(self, score: float, iteration: int, entries) -> None:
        if self.best_entries is None or self.sign * score > self.sign * self.best:
            self.best = score
            self.best_iteration = iteration
            self.best_entries = entries


class _EarlyStopping:
    order = 30
    before_iteration = False

    def __init__(self, stopping_rounds: int, first_metric_only: bool,
                 verbose: bool) -> None:
        self.rounds = stopping_rounds
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.trackers: Dict[int, _MetricTracker] = {}
        self.enabled = True
        self.first_metric = ""
        self._started = False

    # -- setup --

    def _start(self, env: CallbackEnv) -> None:
        self._started = True
        boosting = next((env.params[a] for a in
                         ("boosting", "boosting_type", "boost")
                         if env.params.get(a)), "")
        if boosting == "dart":
            self.enabled = False
            Log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset and "
                             "eval metric is required for evaluation")
        if self.verbose:
            Log.info("Training until validation scores don't improve for %d "
                     "rounds", self.rounds)
        self.first_metric = self._metric_name(env.evaluation_result_list[0])
        for i, entry in enumerate(env.evaluation_result_list):
            self.trackers[i] = _MetricTracker(bigger_better=bool(entry[3]))

    @staticmethod
    def _metric_name(entry) -> str:
        return entry[1].split(" ")[-1]

    def _siblings(self, env: CallbackEnv, i: int):
        """This entry first, then the other entries of the same dataset."""
        mine = env.evaluation_result_list[i]
        rest = [e for j, e in enumerate(env.evaluation_result_list)
                if j != i and e[0] == mine[0]]
        return [mine] + rest

    def _stop(self, tracker: _MetricTracker, metric_name: str, met: bool):
        if self.verbose:
            verb = "Early stopping" if met else "Did not meet early stopping"
            Log.info("%s, best iteration is:\n[%d]\t%s", verb,
                     tracker.best_iteration + 1,
                     "\t".join(_format_eval_result(x)
                               for x in tracker.best_entries))
            if self.first_metric_only:
                Log.info("Evaluated only: %s", metric_name)
        raise EarlyStopException(tracker.best_iteration, tracker.best_entries)

    # -- per-iteration --

    def __call__(self, env: CallbackEnv) -> None:
        if not self._started:
            self._start(env)
        if not self.enabled:
            return
        last = env.iteration == env.end_iteration - 1
        for i, entry in enumerate(env.evaluation_result_list):
            tracker = self.trackers[i]
            tracker.update(entry[2], env.iteration, self._siblings(env, i))
            name = self._metric_name(entry)
            if self.first_metric_only and name != self.first_metric:
                continue
            # training metrics never trigger stopping
            if entry[0] == "training" or (
                    entry[0] == "cv_agg" and entry[1].split(" ")[0] == "train"):
                continue
            if env.iteration - tracker.best_iteration >= self.rounds:
                self._stop(tracker, name, met=True)
            if last:
                self._stop(tracker, name, met=False)


def early_stopping(stopping_rounds, first_metric_only=False, verbose=True):
    """Stop training when no validation metric improved for
    ``stopping_rounds`` consecutive iterations."""
    return _EarlyStopping(stopping_rounds, first_metric_only, verbose)
