"""Fault-injection harness: prove every recovery path of the fault-tolerant
training runtime (lightgbm_tpu/checkpoint.py) actually recovers.

Scenarios (each prints PASS/FAIL and exits nonzero on failure):

  kill-write   Kill the trainer INSIDE an atomic snapshot write — after the
               temp file is written but before the rename (SIGKILL-equivalent
               os._exit in a child process).  Asserts the destination model/
               checkpoint files still validate (atomicity), then resumes the
               run and asserts the final model is bit-identical to an
               uninterrupted run.
  corrupt      Flip bytes in / truncate the NEWEST checkpoint.  Asserts
               load_latest_checkpoint falls back to the previous good one and
               the resumed run still completes.
  nan-grad     Train with gradients that go non-finite at a chosen iteration
               under each nan_policy: raise must raise a LightGBMError,
               skip_iter / clip must complete with a finite model.
  all          Run every scenario.

Small CPU shapes; run with JAX_PLATFORMS=cpu anywhere.  The byte-level
helpers (corrupt_file / truncate_file) are imported by
tests/test_checkpoint.py so the pytest suite and this CLI exercise the same
fault model.
"""
import argparse
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- byte-level fault helpers (shared with tests/test_checkpoint.py) ----

def corrupt_file(path: str, offset: int = None, nbytes: int = 4) -> None:
    """Flip ``nbytes`` bytes in place (default: middle of the file)."""
    size = os.path.getsize(path)
    if offset is None:
        offset = size // 2
    with open(path, "r+b") as fh:
        fh.seek(offset)
        chunk = fh.read(nbytes)
        fh.seek(offset)
        fh.write(bytes(b ^ 0xFF for b in chunk))


def truncate_file(path: str, frac: float = 0.5) -> None:
    """Cut the file to ``frac`` of its size (a partial non-atomic write)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(max(1, int(size * frac)))


# ---- training driver used by every scenario ----

_TRAIN_SRC = r"""
import os, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")

def build(n_iter, snapshot_freq, nan_policy="raise"):
    from lightgbm_tpu.boosting import create_boosting
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.metric.metric import create_metrics
    from lightgbm_tpu.objective import create_objective
    rng = np.random.RandomState(0)
    X = rng.uniform(-2, 2, size=(400, 5))
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 2)
         + 0.1 * rng.normal(size=400)).astype(np.float32)
    cfg = Config(objective="regression", num_leaves=15, min_data_in_leaf=5,
                 bagging_fraction=0.8, bagging_freq=3, verbosity=-1,
                 num_iterations=n_iter, snapshot_freq=snapshot_freq,
                 metric_freq=4, nan_policy=nan_policy)
    ds = BinnedDataset.from_matrix(X, label=y, max_bin=cfg.max_bin,
                                   min_data_in_leaf=cfg.min_data_in_leaf)
    booster = create_boosting(cfg.boosting, cfg,
                              ds, create_objective(cfg.objective, cfg))
    booster.add_train_metrics(create_metrics(cfg.metric, cfg))
    return booster
"""

_KILL_CHILD_SRC = _TRAIN_SRC + r"""
# die like a preempted worker: os._exit inside the atomic write of the
# snapshot at iteration KILL_AT_WRITE_N, after the temp bytes are on disk
# but before the rename
from lightgbm_tpu.utils import file_io
nth = [0]
kill_n = int(os.environ["KILL_AT_WRITE_N"])

def _kill(stage, path):
    if stage != "written":
        return
    nth[0] += 1
    if nth[0] == kill_n:
        os._exit(9)

file_io.set_fault_hook(_kill)
booster = build(int(os.environ["TOTAL_ITERS"]), int(os.environ["SNAP_FREQ"]))
booster.train(snapshot_out=os.environ["MODEL_OUT"])
booster.save_model(os.environ["MODEL_OUT"])
print("TRAINED-TO-END")  # only reached when the kill did not fire
"""


def _run_child(src: str, env: dict) -> subprocess.CompletedProcess:
    full_env = dict(os.environ, JAX_PLATFORMS="cpu", **env)
    return subprocess.run([sys.executable, "-c", src], env=full_env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=900)


def _uninterrupted_model(workdir: str, total: int, sf: int) -> str:
    out = os.path.join(workdir, "ref_model.txt")
    p = _run_child(_KILL_CHILD_SRC, {
        "MODEL_OUT": out, "TOTAL_ITERS": str(total), "SNAP_FREQ": str(sf),
        "KILL_AT_WRITE_N": "0"})
    assert "TRAINED-TO-END" in p.stdout, p.stdout + p.stderr
    with open(out) as fh:
        return fh.read()


def scenario_kill_write(workdir: str) -> None:
    """Kill mid-snapshot-write; assert atomicity + bit-exact resume."""
    total, sf = 20, 7
    ref = _uninterrupted_model(workdir, total, sf)
    out = os.path.join(workdir, "model.txt")
    # 2 snapshot boundaries before total (7, 14); each boundary performs two
    # atomic writes (model snapshot, checkpoint) -> the 3rd write is the
    # iteration-14 model snapshot, the 4th the iteration-14 checkpoint
    p = _run_child(_KILL_CHILD_SRC, {
        "MODEL_OUT": out, "TOTAL_ITERS": str(total), "SNAP_FREQ": str(sf),
        "KILL_AT_WRITE_N": "4"})
    assert p.returncode == 9, "child should have been killed: %s" % p.stderr
    assert "TRAINED-TO-END" not in p.stdout
    # atomicity: everything on disk validates; the interrupted checkpoint
    # write left no trace at the destination
    from lightgbm_tpu.checkpoint import list_checkpoints, load_checkpoint
    ckpts = list_checkpoints(out)
    assert [it for it, _ in ckpts] == [7], ckpts
    load_checkpoint(ckpts[0][1])  # CRC validates
    # resume from the iteration-7 checkpoint and finish
    sys.path.insert(0, REPO)
    ns = {}
    exec(compile(_TRAIN_SRC, "<train>", "exec"), ns)
    booster = ns["build"](total, sf)
    resumed = booster.resume_from_checkpoint(out)
    assert resumed == 7, resumed
    booster.train()
    assert booster.save_model_to_string() == ref, \
        "resumed model diverged from the uninterrupted run"
    print("PASS kill-write: mid-write kill left only valid files; resume "
          "from iter %d is bit-exact" % resumed)


def scenario_corrupt(workdir: str) -> None:
    """Corrupt / truncate the newest checkpoint; assert fallback."""
    out = os.path.join(workdir, "model_c.txt")
    p = _run_child(_KILL_CHILD_SRC, {
        "MODEL_OUT": out, "TOTAL_ITERS": "20", "SNAP_FREQ": "7",
        "KILL_AT_WRITE_N": "0"})
    assert "TRAINED-TO-END" in p.stdout, p.stdout + p.stderr
    from lightgbm_tpu.checkpoint import (CheckpointError, list_checkpoints,
                                         load_checkpoint,
                                         load_latest_checkpoint)
    ckpts = list_checkpoints(out)
    assert len(ckpts) == 2, ckpts  # iterations 14 and 7
    corrupt_file(ckpts[0][1])
    try:
        load_checkpoint(ckpts[0][1])
        raise AssertionError("corrupt checkpoint validated")
    except CheckpointError:
        pass
    meta, _, _, path = load_latest_checkpoint(out)
    assert path == ckpts[1][1] and meta["iteration"] == 7, (path, meta)
    truncate_file(ckpts[1][1], 0.3)
    assert load_latest_checkpoint(out) is None
    print("PASS corrupt: bit-flipped latest fell back to the previous good "
          "checkpoint; truncated survivors are rejected, not mis-loaded")


_NAN_CHILD_SRC = _TRAIN_SRC + r"""
# inject a non-finite gradient batch at iteration NAN_AT via the objective
booster = build(12, -1, nan_policy=os.environ["NAN_POLICY"])
nan_at = int(os.environ["NAN_AT"])
obj = booster.objective
orig = obj.get_gradients
state = {"it": 0}

def poisoned(score):
    g, h = orig(score)
    import jax.numpy as jnp
    if state["it"] == nan_at:
        g = g.at[:7].set(jnp.nan)
    state["it"] += 1
    return g, h

obj.get_gradients = poisoned
booster._fuse_failed = True  # host objective hook: keep per-iteration path
try:
    booster.train()
except Exception as exc:
    print("RAISED %s" % type(exc).__name__)
    sys.exit(0)
import numpy as np
score = np.asarray(booster.train_score)
print("COMPLETED trees=%d finite=%s" % (booster.num_trees,
                                        bool(np.isfinite(score).all())))
"""


def scenario_nan_grad(workdir: str) -> None:
    """NaN gradients at iteration 5 under each nan_policy."""
    for policy, want in [("raise", "RAISED LightGBMError"),
                         ("skip_iter", "COMPLETED trees=12 finite=True"),
                         ("clip", "COMPLETED trees=12 finite=True")]:
        p = _run_child(_NAN_CHILD_SRC, {"NAN_POLICY": policy, "NAN_AT": "5"})
        assert want in p.stdout, (policy, p.stdout, p.stderr[-2000:])
        print("PASS nan-grad[%s]: %s" % (policy, want))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fault-injection harness for the checkpoint/resume "
                    "runtime (kill mid-write, corrupt/truncate, NaN "
                    "gradients)")
    ap.add_argument("scenario", nargs="?", default="all",
                    choices=["all", "kill-write", "corrupt", "nan-grad"])
    ap.add_argument("--workdir", default=None,
                    help="scratch directory (default: a fresh tempdir)")
    args = ap.parse_args(argv)
    import tempfile
    workdir = args.workdir or tempfile.mkdtemp(prefix="lgbm_fault_")
    sys.path.insert(0, REPO)
    scenarios = {"kill-write": scenario_kill_write,
                 "corrupt": scenario_corrupt,
                 "nan-grad": scenario_nan_grad}
    names = list(scenarios) if args.scenario == "all" else [args.scenario]
    for name in names:
        scenarios[name](workdir)
    print("ALL FAULT SCENARIOS PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
