"""Multi-chip tree learners: XLA collectives over a device mesh.

TPU-native counterpart of the reference's parallel tree learners and socket/MPI
Network layer (src/treelearner/{data,feature,voting}_parallel_tree_learner.cpp,
src/network/) — see lightgbm_tpu/core/tree_learner.py:Comm for the mapping.
"""
from .learners import (DataParallelTreeLearner,
                       FeatureParallelTreeLearner,
                       PartitionedDataParallelTreeLearner,
                       VotingParallelTreeLearner, create_tree_learner,
                       default_mesh, is_write_leader, sharded_contrib_fn,
                       sharded_predict, sharded_predict_contrib,
                       sharded_predict_fn)

__all__ = [
    "DataParallelTreeLearner",
    "FeatureParallelTreeLearner", "PartitionedDataParallelTreeLearner",
    "VotingParallelTreeLearner", "create_tree_learner", "default_mesh",
    "is_write_leader", "sharded_contrib_fn", "sharded_predict",
    "sharded_predict_contrib", "sharded_predict_fn",
]
