"""Fused split pass: routing + stable partition + child histogram in ONE
Pallas kernel invocation per split.

Counterpart of the reference's per-split trio — ``DataPartition::Split``
(src/treelearner/data_partition.hpp:113), the ordered-index histogram
(src/io/dense_bin.hpp:48 ConstructHistogram over begin..end), and the GPU
learner's copy/kernel overlap (src/treelearner/gpu_tree_learner.cpp:952-1055)
— rebuilt for the TPU memory system:

- XLA's row scatter costs ~5-10 ns/row in per-row DMA descriptors, and the
  bucketed ``lax.switch`` the round-3 builder used forced buffer-unification
  copies of the whole row store every split (PERF.md).  Together those were
  ~45% of every boosting iteration.
- This kernel instead streams the parent leaf's window through VMEM in
  ``CHUNK``-row double-buffered tiles, routes each row (same binned-decision
  semantics as ``tree_learner._route_left``), and *places* rows with a one-hot
  permutation matmul on the MXU — left rows compact to the window's front
  (in-place, behind the read cursor), right rows stream to a scratch region
  and are copied back after the left block settles.  Every HBM touch is a
  contiguous >=64 KB DMA at a 32-row-aligned offset: zero per-row descriptors,
  no switch, cost proportional to the window, a single compiled code path for
  every window size (which also keeps program size flat in N — the round-3
  bucketed switch grew it).
- The smaller child's histogram (serial_tree_learner.cpp:347-356 subtraction
  trick feeds on it) accumulates in the same pass from the same VMEM tiles —
  the routing/scatter/histogram fusion PERF.md round 3 listed as the next
  lever.

Mosaic constraints honored (probed on v5e): no u8 vector arithmetic (u8 used
only for DMA/select; math in i32/bf16/f32), no dynamic sublane rotate on u8
(placement is done by matmul, not roll), dynamic DMA offsets must be provably
32-row aligned (``pl.multiple_of`` + by-construction alignment).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram import (_accum_onehot_tiles, _f32_from_bytes, _hilo_split,
                        _padded_features, histogram_xla_masked, rows_split_xla)

# f32 extraction must use the weighted-lane reduction form; see the Mosaic
# miscompilation note on histogram._f32_from_bytes
_f32_at = _f32_from_bytes

_LANE = 128
_ALIGN = 32          # u8 sublane tile: dynamic DMA offsets must be 32-row mult
CHUNK = 2048         # rows per streamed DMA tile
T = 512              # rows per placement subtile (one P matmul)
TS = 512             # staging/flush tile (rows per contiguous write-back)
# The single-flush circular staging depends on nls <= TS per subtile (at most
# one stage wrap per append) and the subtile loop covering the chunk exactly;
# retuning one constant without the other silently corrupts the partition.
assert T == TS and CHUNK % T == 0 and T % _ALIGN == 0 and TS % _ALIGN == 0


def _cumsum_tri(ltri_ref, sel_f):
    """Inclusive prefix sum of a [T, 1] f32 0/1 vector via a lower-triangular
    ones matmul (vector-form cumsum over sublanes is vreg-padded ~64x on TPU;
    one tiny MXU matmul is cheaper)."""
    return jax.lax.dot_general(
        ltri_ref[...], sel_f, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [T, 1]


def _extract_col(ti, gcol, *, W, bpc, packed):
    """Bin code of group column ``gcol`` (dynamic) from an i32 row-store tile
    ``ti`` [T, W] -> [T, 1] i32.  Mirrors tree_learner.col_from_rows."""
    lanes = jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)
    if packed:
        byte = jnp.sum(ti * (lanes == gcol // 2), axis=1, keepdims=True)
        return jnp.where(gcol % 2 == 1, (byte >> 4) & 15, byte & 15)
    if bpc == 2:
        lo = jnp.sum(ti * (lanes == 2 * gcol), axis=1, keepdims=True)
        hi = jnp.sum(ti * (lanes == 2 * gcol + 1), axis=1, keepdims=True)
        return lo | (hi << 8)
    return jnp.sum(ti * (lanes == gcol), axis=1, keepdims=True)


def _route_tile(col, scal_ref, num_bins):
    """go-left decision as a [T, 1] i32 0/1 vector (Mosaic cannot truncate i8
    vectors to i1, so boolean logic stays in i32 arithmetic); scalar split
    description from SMEM (bitset words ride in scal[12:] as i32).  Same
    semantics as tree_learner._route_left (tree.h:262-331)."""
    thr = scal_ref[3]
    default_left = scal_ref[4]
    mt = scal_ref[5]
    nb = scal_ref[6]
    dbin = scal_ref[7]
    is_cat = scal_ref[8] == 1
    use_unfold = scal_ref[10] == 1
    eoff = scal_ref[11]
    # EFB group code -> feature bin (tree_learner._unfold_bin)
    in_range = ((col >= eoff).astype(jnp.int32)
                * (col <= eoff + nb - 2).astype(jnp.int32))
    unfolded = jnp.where(in_range == 1, col - eoff + 1, 0)
    col = jnp.where(use_unfold, unfolded, col)
    is_missing = jnp.where(
        mt == 1, (col == nb - 1).astype(jnp.int32),          # MissingType.NAN
        jnp.where(mt == 2, (col == dbin).astype(jnp.int32),  # MissingType.ZERO
                  jnp.zeros_like(col)))
    num_left = jnp.where(is_missing == 1,
                         jnp.full_like(col, 1) * default_left,
                         (col <= thr).astype(jnp.int32))
    # categorical: bin membership in the left bitset words
    word = jnp.zeros_like(col)
    for wd in range(num_bins // 32):
        word = jnp.where((col >> 5) == wd, scal_ref[12 + wd], word)
    cat_left = (word >> (col & 31)) & 1
    return jnp.where(is_cat, cat_left, num_left)




def _make_partition_kernel(*, n_pad, W, num_features, num_bins, voff, bpc,
                           packed, exact):
    del n_pad  # shapes come from the refs; kept for cache-key clarity

    def kernel(scal_ref, rows_in_ref, rows_ref, scratch_ref, hist_ref,
               stats_ref, inbuf, stage, ltri, rot, tmp,
               sem_in, sem_pre, sem_fl, sem_fr, sem_cb):
        # rows_in_ref is the pre-alias view of rows_ref (same buffer); all
        # reads and writes go through rows_ref so ordering is explicit
        del rows_in_ref
        wb = scal_ref[0]
        wc = scal_ref[1]
        gcol = scal_ref[2]
        hist_left = scal_ref[9]

        wb_al = pl.multiple_of((wb // _ALIGN) * _ALIGN, _ALIGN)
        headL = wb - wb_al
        nchunks = (headL + wc + CHUNK - 1) // CHUNK

        hist_ref[...] = jnp.zeros_like(hist_ref)
        # lower-triangular ones (inclusive prefix-sum operator)
        ltri[...] = (jax.lax.broadcasted_iota(jnp.int32, (T, 1), 0)
                     >= jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
                     ).astype(jnp.bfloat16)

        # prefill the left stage's head with the old rows [wb_al, wb) so the
        # first aligned flush preserves the neighbour leaf's rows
        cp = pltpu.make_async_copy(
            rows_ref.at[pl.ds(wb_al, _ALIGN)], stage.at[pl.ds(0, _ALIGN)],
            sem_pre)
        cp.start()
        cp.wait()

        @pl.when(nchunks > 0)
        def _prologue():
            pltpu.make_async_copy(
                rows_ref.at[pl.ds(wb_al, CHUNK)], inbuf.at[0], sem_in.at[0]
            ).start()

        iota2ts = jax.lax.broadcasted_iota(jnp.int32, (2 * TS, 1), 0)
        iota1x2ts = jax.lax.broadcasted_iota(jnp.int32, (1, 2 * TS), 1)
        iota_t = jax.lax.broadcasted_iota(jnp.int32, (T, 1), 0)

        def chunk_body(c, carry):
            fillL, fillR, nfL, nfR = carry
            slot = jax.lax.rem(c, 2)
            pltpu.make_async_copy(
                rows_ref.at[pl.ds(pl.multiple_of(wb_al + c * CHUNK, _ALIGN),
                                  CHUNK)],
                inbuf.at[slot], sem_in.at[slot]).wait()

            @pl.when(c + 1 < nchunks)
            def _prefetch():
                nxt = 1 - slot
                pltpu.make_async_copy(
                    rows_ref.at[pl.ds(
                        pl.multiple_of(wb_al + (c + 1) * CHUNK, _ALIGN),
                        CHUNK)],
                    inbuf.at[nxt], sem_in.at[nxt]).start()

            abs0 = wb_al + c * CHUNK
            for s in range(CHUNK // T):
                tile = inbuf[slot, s * T:(s + 1) * T, :]        # [T, W] u8
                ti = tile.astype(jnp.int32)
                col = _extract_col(ti, gcol, W=W, bpc=bpc, packed=packed)
                gl = _route_tile(col, scal_ref, num_bins)        # i32 0/1
                pos = abs0 + s * T + iota_t
                inw = ((pos >= wb).astype(jnp.int32)
                       * (pos < wb + wc).astype(jnp.int32))
                selL = gl * inw                                  # i32 0/1
                selR = (1 - gl) * inw
                pfxL = _cumsum_tri(ltri, selL.astype(jnp.float32)
                                   ).astype(jnp.int32)           # [T, 1]
                pfxR = _cumsum_tri(ltri, selR.astype(jnp.float32)
                                   ).astype(jnp.int32)
                nls = pfxL[T - 1, 0]
                nrs = pfxR[T - 1, 0]
                startL = jax.lax.rem(headL + fillL, TS)
                startR = jax.lax.rem(fillR, TS)
                destL = jax.lax.rem(startL + pfxL - 1, TS)
                destR = TS + jax.lax.rem(startR + pfxR - 1, TS)
                dest = jnp.where(selL == 1, destL,
                                 jnp.where(selR == 1, destR, 2 * TS))
                Pt = (dest == iota1x2ts).astype(jnp.bfloat16)    # [T, 2TS]
                comp_f = jax.lax.dot_general(
                    Pt, ti.astype(jnp.bfloat16),
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)          # [2TS, W]
                comp = comp_f.astype(jnp.int32).astype(jnp.uint8)

                # blend the unwrapped circular ranges of both sides (masks in
                # i32: Mosaic cannot truncate i8 bool vectors to i1)
                pL = iota2ts
                pR = iota2ts - TS
                mask_u = jnp.where(
                    iota2ts < TS,
                    (pL >= startL).astype(jnp.int32)
                    * (pL < startL + nls).astype(jnp.int32),
                    (pR >= startR).astype(jnp.int32)
                    * (pR < startR + nrs).astype(jnp.int32))
                stage[...] = jnp.where(mask_u == 1, comp, stage[...])

                crossL = startL + nls >= TS
                crossR = startR + nrs >= TS

                @pl.when(crossL)
                def _flush_left():
                    cpf = pltpu.make_async_copy(
                        stage.at[pl.ds(0, TS)],
                        rows_ref.at[pl.ds(
                            pl.multiple_of(wb_al + nfL * TS, _ALIGN), TS)],
                        sem_fl)
                    cpf.start()
                    cpf.wait()

                @pl.when(crossR)
                def _flush_right():
                    cpf = pltpu.make_async_copy(
                        stage.at[pl.ds(TS, TS)],
                        scratch_ref.at[pl.ds(
                            pl.multiple_of(nfR * TS, _ALIGN), TS)],
                        sem_fr)
                    cpf.start()
                    cpf.wait()

                # wrapped parts land in the freshly flushed tile
                mask_w = jnp.where(
                    iota2ts < TS,
                    (pL < startL + nls - TS).astype(jnp.int32),
                    (pR < startR + nrs - TS).astype(jnp.int32))
                stage[...] = jnp.where(mask_w == 1, comp, stage[...])

                # smaller child's histogram from the same tile
                sf = jnp.where(hist_left == 1, selL.astype(jnp.float32),
                               selR.astype(jnp.float32))
                g = _f32_at(ti, voff) * sf
                h = _f32_at(ti, voff + 4) * sf
                vals = jnp.concatenate([g, h], axis=1)           # [T, 2]
                v4 = _hilo_split(vals, axis=1, exact=exact)

                def colf(f):
                    if packed:
                        return (ti[:, f // 2:f // 2 + 1] >> (4 * (f % 2))) & 15
                    if bpc == 2:
                        return (ti[:, 2 * f:2 * f + 1]
                                | (ti[:, 2 * f + 1:2 * f + 2] << 8))
                    return ti[:, f:f + 1]

                _accum_onehot_tiles(colf, v4, hist_ref,
                                    num_features=num_features,
                                    num_bins=num_bins, contract_dim=0)

                fillL = fillL + nls
                fillR = fillR + nrs
                nfL = nfL + jnp.where(crossL, 1, 0)
                nfR = nfR + jnp.where(crossR, 1, 0)
            return fillL, fillR, nfL, nfR

        zero = jnp.int32(0)
        fillL, fillR, nfL, nfR = jax.lax.fori_loop(
            0, nchunks, chunk_body, (zero, zero, zero, zero))
        nl = fillL
        nr = fillR
        stats_ref[0, 0] = nl

        # ---- final right partial flush (scratch is all ours: no RMW,
        # garbage tail rows are masked by nr during copy-back) ----
        pend_r = fillR - nfR * TS

        @pl.when(pend_r > 0)
        def _final_right():
            cpf = pltpu.make_async_copy(
                stage.at[pl.ds(TS, TS)],
                scratch_ref.at[pl.ds(pl.multiple_of(nfR * TS, _ALIGN), TS)],
                sem_fr)
            cpf.start()
            cpf.wait()

        # ---- final left partial flush (read-modify-write) ----
        pend_l = headL + fillL - nfL * TS

        @pl.when(pend_l > 0)
        def _final_left():
            src = pl.multiple_of(wb_al + nfL * TS, _ALIGN)
            cpa = pltpu.make_async_copy(rows_ref.at[pl.ds(src, TS)],
                                        tmp, sem_fl)
            cpa.start()
            cpa.wait()
            keep = jax.lax.broadcasted_iota(jnp.int32, (TS, 1), 0) < pend_l
            tmp[...] = jnp.where(keep, stage[0:TS, :], tmp[...])
            cpb = pltpu.make_async_copy(tmp, rows_ref.at[pl.ds(src, TS)],
                                        sem_fl)
            cpb.start()
            cpb.wait()

        # ---- copy right block back: scratch[0:nr] -> rows[wb+nl ...) ----
        @pl.when(nr > 0)
        def _copy_back():
            d0 = wb + nl
            d_al = pl.multiple_of((d0 // _ALIGN) * _ALIGN, _ALIGN)
            ph = d0 - d_al
            # constant row-rotation one-hot: source row j -> stage (j+ph)%TS
            rot[...] = (jax.lax.rem(
                jax.lax.broadcasted_iota(jnp.int32, (TS, 1), 0) + ph, TS)
                == jax.lax.broadcasted_iota(jnp.int32, (1, TS), 1)
            ).astype(jnp.bfloat16)
            # head prefill: keep rows [d_al, d0) (tail of the left block)
            cph = pltpu.make_async_copy(
                rows_ref.at[pl.ds(d_al, _ALIGN)],
                stage.at[pl.ds(0, _ALIGN)], sem_pre)
            cph.start()
            cph.wait()
            ncb = (nr + TS - 1) // TS
            iota_ts = jax.lax.broadcasted_iota(jnp.int32, (TS, 1), 0)

            def cb_body(k, carry):
                fill, nf = carry
                cpi = pltpu.make_async_copy(
                    scratch_ref.at[pl.ds(
                        pl.multiple_of(k * TS, _ALIGN), TS)],
                    tmp, sem_cb)
                cpi.start()
                cpi.wait()
                tr = jax.lax.dot_general(
                    rot[...], tmp[...].astype(jnp.int32).astype(jnp.bfloat16),
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                comp = tr.astype(jnp.int32).astype(jnp.uint8)    # [TS, W]
                nvs = jnp.minimum(nr - k * TS, TS)
                start = jax.lax.rem(ph + fill, TS)               # == ph
                # valid source rows j < nvs sit at p=(ph+j)%TS
                pj = jax.lax.rem(iota_ts - ph + TS, TS)          # j of pos p
                mask_u = ((iota_ts >= start).astype(jnp.int32)
                          * (pj < nvs).astype(jnp.int32))
                stage[0:TS, :] = jnp.where(mask_u == 1, comp, stage[0:TS, :])
                cross = start + nvs >= TS

                @pl.when(cross)
                def _flush_cb():
                    cpf = pltpu.make_async_copy(
                        stage.at[pl.ds(0, TS)],
                        rows_ref.at[pl.ds(
                            pl.multiple_of(d_al + nf * TS, _ALIGN), TS)],
                        sem_cb)
                    cpf.start()
                    cpf.wait()

                mask_w = ((iota_ts < start).astype(jnp.int32)
                          * (pj < nvs).astype(jnp.int32))
                stage[0:TS, :] = jnp.where(mask_w == 1, comp, stage[0:TS, :])
                return fill + nvs, nf + jnp.where(cross, 1, 0)

            fill, nf = jax.lax.fori_loop(0, ncb, cb_body, (zero, zero))
            pend = ph + fill - nf * TS

            @pl.when(pend > 0)
            def _final_cb():
                src = pl.multiple_of(d_al + nf * TS, _ALIGN)
                cpa = pltpu.make_async_copy(rows_ref.at[pl.ds(src, TS)],
                                            tmp, sem_cb)
                cpa.start()
                cpa.wait()
                keep = jax.lax.broadcasted_iota(jnp.int32, (TS, 1), 0) < pend
                tmp[...] = jnp.where(keep, stage[0:TS, :], tmp[...])
                cpb = pltpu.make_async_copy(tmp, rows_ref.at[pl.ds(src, TS)],
                                            sem_cb)
                cpb.start()
                cpb.wait()

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "num_features", "num_bins", "voff", "bpc", "packed", "exact", "interpret"))
def partition_hist_pallas(rows: jax.Array, scal: jax.Array,
                          *, num_features: int,
                          num_bins: int, voff: int, bpc: int = 1,
                          packed: bool = False, exact: bool = False,
                          interpret: bool = False):
    """Fused split pass over a combined row store.

    rows: [N_pad, W] u8 row store, N_pad a multiple of CHUNK.  CONTRACT: the
      caller must keep every window end <= N_pad - CHUNK (the streaming loop
      reads and the copy-back RMW writes up to a CHUNK past the window end);
      the tree builder guarantees it by always padding a full spare CHUNK.
    scal: i32 [12 + num_bins//32]: (window_begin, window_count, group_col,
      threshold_bin, default_left, missing_type, num_bin_f, default_bin,
      is_cat, hist_left_side, use_unfold, efb_offset, *cat_bitset_words).

    Returns (rows_new [N_pad, W] u8 — the window stably partitioned in place,
    hist4 [4, f_pad*num_bins] f32 — smaller child's histogram, hi/lo rows to
    fold like histogram_pallas_rows, nl [1, 1] i32 — left-child row count).
    """
    n_pad, W = rows.shape
    assert n_pad % CHUNK == 0, "pad the row store to a multiple of CHUNK"
    assert num_bins >= 32 and num_bins % 32 == 0, \
        "num_bins must be the >=32 kernel-block width (_pad_bins_pow2); " \
        "nibble-packed 16-bin data still scans at 32 lanes"
    f_pad = _padded_features(num_features, num_bins)
    lanes = f_pad * num_bins
    kernel = _make_partition_kernel(
        n_pad=n_pad, W=W, num_features=num_features, num_bins=num_bins,
        voff=voff, bpc=bpc, packed=packed, exact=exact)
    rows_new, _scratch, hist, nl = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),       # rows
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),       # rows out (aliased)
                pl.BlockSpec(memory_space=pl.ANY),       # right-block scratch
                pl.BlockSpec(memory_space=pltpu.VMEM),   # hist
                pl.BlockSpec(memory_space=pltpu.SMEM),   # nl
            ],
            scratch_shapes=[
                pltpu.VMEM((2, CHUNK, W), jnp.uint8),    # streamed chunks
                pltpu.VMEM((2 * TS, W), jnp.uint8),      # L/R circular stages
                pltpu.VMEM((T, T), jnp.bfloat16),        # lower-tri ones
                pltpu.VMEM((TS, TS), jnp.bfloat16),      # copy-back rotation
                pltpu.VMEM((TS, W), jnp.uint8),          # RMW bounce
                pltpu.SemaphoreType.DMA((2,)),           # chunk reads
                pltpu.SemaphoreType.DMA,                 # prefills
                pltpu.SemaphoreType.DMA,                 # left flushes
                pltpu.SemaphoreType.DMA,                 # right flushes
                pltpu.SemaphoreType.DMA,                 # copy-back
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, W), jnp.uint8),
            jax.ShapeDtypeStruct((n_pad, W), jnp.uint8),
            jax.ShapeDtypeStruct((4, lanes), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        input_output_aliases={1: 0},
        interpret=interpret,
    )(scal, rows)
    return rows_new, hist, nl


def fold_hist(hist4: jax.Array, num_features: int, num_bins: int) -> jax.Array:
    """[4, f_pad*B] hi/lo rows -> [F, 2, B] f32 (same fold as
    histogram_pallas_rows)."""
    f_pad = _padded_features(num_features, num_bins)
    folded = hist4[0:2] + hist4[2:4]
    return folded.reshape(2, f_pad, num_bins).transpose(1, 0, 2)[:num_features]


def partition_hist_xla(rows: jax.Array, scal, *,
                       num_features: int, num_bins: int, voff: int,
                       bpc: int = 1, packed: bool = False):
    """Reference implementation of the kernel's contract in plain XLA ops
    (full-array mask + cumsum + scatter).  Used by tests and as the
    documentation of the output semantics; the production non-TPU path stays
    on the bucketed-switch builder."""
    assert num_bins >= 32 and num_bins % 32 == 0, \
        "num_bins must be the >=32 kernel-block width (_pad_bins_pow2)"
    n, W = rows.shape
    wb, wc, gcol, thr, dleft, mt, nb, dbin, is_cat, hist_left, use_unfold, \
        eoff = [scal[i] for i in range(12)]
    bitset_words = scal[None, 12:12 + num_bins // 32]
    ri = rows.astype(jnp.int32)
    if packed:
        byte = jnp.take_along_axis(
            ri, jnp.full((n, 1), gcol // 2, jnp.int32), axis=1)[:, 0]
        col = jnp.where(gcol % 2 == 1, (byte >> 4) & 15, byte & 15)
    elif bpc == 2:
        lo = jnp.take_along_axis(ri, jnp.full((n, 1), 2 * gcol, jnp.int32),
                                 axis=1)[:, 0]
        hi = jnp.take_along_axis(ri, jnp.full((n, 1), 2 * gcol + 1,
                                              jnp.int32), axis=1)[:, 0]
        col = lo | (hi << 8)
    else:
        col = jnp.take_along_axis(ri, jnp.full((n, 1), gcol, jnp.int32),
                                  axis=1)[:, 0]
    unfolded = jnp.where((col >= eoff) & (col <= eoff + nb - 2),
                         col - eoff + 1, 0)
    col = jnp.where(use_unfold == 1, unfolded, col)
    is_missing = jnp.where(mt == 1, col == nb - 1,
                           jnp.where(mt == 2, col == dbin, False))
    num_left = jnp.where(is_missing, dleft == 1, col <= thr)
    word = bitset_words[0][jnp.clip(col >> 5, 0, bitset_words.shape[1] - 1)]
    cat_left = ((word.astype(jnp.uint32)
                 >> (col & 31).astype(jnp.uint32)) & 1) == 1
    gl = jnp.where(is_cat == 1, cat_left, num_left)

    iota = jnp.arange(n, dtype=jnp.int32)
    inw = (iota >= wb) & (iota < wb + wc)
    selL = gl & inw
    selR = (~gl) & inw
    nl = jnp.sum(selL, dtype=jnp.int32)
    cl = jnp.cumsum(selL, dtype=jnp.int32)
    cr = jnp.cumsum(selR, dtype=jnp.int32)
    dest = jnp.where(selL, wb + cl - 1,
                     jnp.where(selR, wb + nl + cr - 1, iota))
    rows_new = jnp.zeros_like(rows).at[dest].set(rows, unique_indices=True)

    side = jnp.where(hist_left == 1, selL, selR)
    bins, values = rows_split_xla(rows, num_features, voff, bpc, packed)
    hist = histogram_xla_masked(bins, values * side.astype(jnp.float32)[None],
                                num_bins, jnp.int32(0), jnp.int32(n))
    return rows_new, hist, nl
