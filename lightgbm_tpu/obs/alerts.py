"""Live SLO alerting: declarative rules + burn-rate windows over the run.

``PERF_BUDGETS.json`` declares the repo's perf invariants, but
``tools/perf_gate.py`` only enforces them post-mortem — a live p99 breach
or a reject burst is invisible until the run exits.  This module
evaluates the SAME declarative shape continuously against the live
:class:`~.registry.MetricsRegistry` snapshot:

- rules live in a JSON file (``alert_rules=<path>`` param; the repo's
  ``PERF_BUDGETS.json`` carries a default ``"alerts"`` list, so one file
  feeds both the post-mortem gate and the live engine);
- **multiwindow burn rates**: each rule is judged over a FAST and a SLOW
  window (SRE-style multiwindow alerting) and fires only when both burn —
  a single slow scrape cannot page anyone, and a sustained breach cannot
  hide behind an old healthy average;
- surfacing: ``GET /alerts`` on the exporter (live JSON state),
  ``kind="alert"`` JSONL events on every transition (died-run recovery in
  ``tools/obs_report.py`` rebuilds the section from them), the
  ``alerts_fired`` counter (``tools/perf_gate.py`` budgets it to 0 on
  healthy baseline artifacts), and the flight recorder
  (:func:`~.profiling.on_incident`) on the first firing.

Rule kinds (all windows/thresholds optional with the defaults below)::

    {"name": "serve_p99", "kind": "quantile",
     "metric": "serve_latency_s_model_*", "quantile": "p99", "max": 0.5,
     "budget": 0.1, "fast_window_s": 60, "slow_window_s": 300,
     "burn_threshold": 1.0, "severity": "page"}
    {"name": "reject_rate", "kind": "rate", "counter": "serve_rejected",
     "max_per_s": 0.0, "fast_window_s": 60, "slow_window_s": 300}
    {"name": "queue", "kind": "gauge", "gauge": "serve_queue_depth",
     "max": 512}

``quantile``/``gauge`` rules sample the watched value each tick and judge
the BREACH FRACTION of each window against ``budget`` (the allowed bad
fraction; 0 = any breach burns infinitely).  ``rate`` rules watch a
cumulative counter and judge its windowed per-second rate against
``max_per_s``.  Burn = observed / allowed, clamped to
:data:`BURN_CAP`; a rule fires when both windows' burns reach
``burn_threshold``.

Quantile-rule semantics caveat: registry histograms are RUN-CUMULATIVE
(a bounded uniform reservoir over every observation — obs/registry.py),
so a quantile rule watches "is the run's p99 currently breaching", not a
windowed p99.  Late in a very long run the cumulative quantile moves
slowly: a regression must contribute meaningful reservoir mass before it
crosses the bar, and it dilutes back just as slowly.  For
fast-twitch detection prefer ``rate`` rules (truly windowed) or restart
the statistic with the run.  Two mitigations are built in: a quantile
series only records a new window sample when its histogram saw NEW
observations since the previous tick (an idle series neither re-fires
nor holds a stale alert open), and once every bad sample ages out of
both windows the alert resolves — silence is "no verdict", not "still
firing".

Run-owned, zero-overhead-when-off: the engine thread exists only when a
run installed one (``tele.alerts``); ``Telemetry.close()`` stops it.
Pure window math (:func:`breach_fraction`, :func:`burn_rate`,
:func:`window_rate`) is exposed for the hand-computed goldens in
tests/test_obs_forensics.py.
"""
from __future__ import annotations

import fnmatch
import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils.log import Log

DEFAULT_INTERVAL_S = 1.0
DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 300.0
DEFAULT_BURN_THRESHOLD = 1.0
# burns are clamped finite so /alerts JSON and telemetry events stay
# strictly valid (a zero budget would otherwise emit Infinity)
BURN_CAP = 1e6

_KINDS = ("quantile", "rate", "gauge")


# ---- pure window math (hand-computed goldens live on these) ----

def breach_fraction(samples, now: float, window_s: float) -> Optional[float]:
    """Fraction of ``(ts, bad)`` samples with ``ts > now - window_s`` that
    are bad; None when the window holds no samples."""
    n = bad = 0
    lo = now - float(window_s)
    for ts, is_bad in samples:
        if ts > lo:
            n += 1
            if is_bad:
                bad += 1
    return (bad / n) if n else None


def burn_rate(fraction: Optional[float], budget: float) -> Optional[float]:
    """Observed bad fraction over the allowed fraction, clamped to
    :data:`BURN_CAP`; a zero budget burns at the cap the moment anything
    is bad.  None passes through (no data = no verdict)."""
    if fraction is None:
        return None
    if budget > 0:
        return min(fraction / budget, BURN_CAP)
    return BURN_CAP if fraction > 0 else 0.0


def window_rate(points, now: float, window_s: float) -> float:
    """Per-second rate of a cumulative counter over the window: the
    latest point minus the window's baseline (the newest point at or
    before the window start, else the oldest in-window point) over their
    time span.  0.0 with fewer than two points."""
    lo = now - float(window_s)
    base = None
    last = None
    for ts, c in points:
        if ts <= lo:
            base = (ts, c)
        else:
            if base is None:
                base = (ts, c)
            last = (ts, c)
    if base is None or last is None or last[0] <= base[0]:
        return 0.0
    return max(float(last[1]) - float(base[1]), 0.0) / (last[0] - base[0])


# ---- rules ----

def load_rules(path: str) -> List[Dict[str, Any]]:
    """Rules from a JSON file: either a bare list or a dict carrying an
    ``"alerts"`` list (the PERF_BUDGETS.json shape).  Unknown kinds are
    dropped with a warning, never an error — a typo in one rule must not
    take live alerting down with it."""
    with open(path) as fh:
        doc = json.load(fh)
    raw = doc.get("alerts", []) if isinstance(doc, dict) else doc
    rules = []
    for r in raw or []:
        if not isinstance(r, dict) or not r.get("name"):
            Log.warning("alert rule without a name dropped: %r", r)
            continue
        if r.get("kind") not in _KINDS:
            Log.warning("alert rule %r has unknown kind %r (expected %s); "
                        "dropped", r.get("name"), r.get("kind"),
                        "/".join(_KINDS))
            continue
        rules.append(dict(r))
    return rules


class AlertEngine:
    """Periodic rule evaluation over one run's registry snapshot.

    ``clock`` is injectable (tests drive :meth:`tick` with hand times);
    the background thread only exists after :meth:`start`."""

    def __init__(self, tele, rules: List[Dict[str, Any]],
                 interval_s: float = DEFAULT_INTERVAL_S,
                 clock=time.monotonic) -> None:
        self.tele = tele
        self.rules = list(rules)
        self.interval_s = max(float(interval_s), 0.05)
        self.clock = clock
        self.fired_total = 0
        self.external: Dict[str, int] = {}
        self._series: Dict[tuple, deque] = {}
        self._state: Dict[tuple, Dict[str, Any]] = {}
        # per-quantile-series histogram count at the last tick: a series
        # with no NEW observations contributes no new window sample (the
        # cumulative quantile would otherwise re-assert stale state
        # forever — see the module docstring caveat)
        self._last_counts: Dict[tuple, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ----

    def start(self) -> "AlertEngine":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="lgbm-tpu-alerts")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as exc:  # the engine must never kill the run
                Log.warning("alert evaluation failed: %s: %s",
                            type(exc).__name__, exc)

    # ---- evaluation ----

    def _windows(self, rule) -> tuple:
        return (float(rule.get("fast_window_s", DEFAULT_FAST_WINDOW_S)),
                float(rule.get("slow_window_s", DEFAULT_SLOW_WINDOW_S)))

    def _match(self, pattern: str, names) -> List[str]:
        if any(ch in pattern for ch in "*?["):
            return sorted(fnmatch.filter(names, pattern))
        return [pattern] if pattern in names else []

    def tick(self, now: Optional[float] = None) -> None:
        """One evaluation pass (the thread calls this every interval;
        tests call it directly with a pinned ``now``)."""
        now = self.clock() if now is None else float(now)
        snap = self.tele.registry.snapshot()
        for i, rule in enumerate(self.rules):
            kind = rule["kind"]
            fast_w, slow_w = self._windows(rule)
            keep_s = max(fast_w, slow_w) * 1.25
            if kind == "quantile":
                hists = snap.get("histograms", {})
                for name in self._match(rule.get("metric", ""), hists):
                    h = hists[name]
                    if not h.get("count"):
                        continue
                    val = h.get(rule.get("quantile", "p99"))
                    if val is None:
                        continue
                    with self._lock:
                        last = self._last_counts.get((i, name))
                        self._last_counts[(i, name)] = h["count"]
                    self._judge_fraction(rule, i, name, float(val), now,
                                         fast_w, slow_w, keep_s,
                                         append=last != h["count"])
            elif kind == "gauge":
                gauges = snap.get("gauges", {})
                for name in self._match(rule.get("gauge", ""), gauges):
                    val = gauges[name]
                    if val is None:
                        continue
                    self._judge_fraction(rule, i, name, float(val), now,
                                         fast_w, slow_w, keep_s)
            elif kind == "rate":
                counters = snap.get("counters", {})
                for name in self._match(rule.get("counter", ""), counters):
                    self._judge_rate(rule, i, name, float(counters[name]),
                                     now, fast_w, slow_w, keep_s)

    def _samples(self, key, now: float, keep_s: float) -> deque:
        dq = self._series.get(key)
        if dq is None:
            dq = self._series[key] = deque()
        lo = now - keep_s
        while dq and dq[0][0] <= lo:
            dq.popleft()
        return dq

    def _judge_fraction(self, rule, i, series, value: float, now,
                        fast_w, slow_w, keep_s, append: bool = True) -> None:
        bad = value > float(rule.get("max", float("inf")))
        with self._lock:
            dq = self._samples((i, series), now, keep_s)
            if append:
                dq.append((now, bad))
            samples = list(dq)
        budget = float(rule.get("budget", 0.0))
        fast = burn_rate(breach_fraction(samples, now, fast_w), budget)
        slow = burn_rate(breach_fraction(samples, now, slow_w), budget)
        self._transition(rule, i, series, value, fast, slow, now)

    def _judge_rate(self, rule, i, series, cum: float, now,
                    fast_w, slow_w, keep_s) -> None:
        with self._lock:
            dq = self._samples((i, series), now, keep_s)
            dq.append((now, cum))
            points = list(dq)
        max_per_s = float(rule.get("max_per_s", 0.0))
        fast_r = window_rate(points, now, fast_w)
        slow_r = window_rate(points, now, slow_w)

        def burn(rate):
            if max_per_s > 0:
                return min(rate / max_per_s, BURN_CAP)
            return BURN_CAP if rate > 0 else 0.0
        self._transition(rule, i, series, fast_r, burn(fast_r),
                         burn(slow_r), now)

    def _transition(self, rule, i, series, value, fast, slow, now) -> None:
        threshold = float(rule.get("burn_threshold",
                                   DEFAULT_BURN_THRESHOLD))
        firing = (fast is not None and slow is not None
                  and fast >= threshold and slow >= threshold)
        key = (i, series)
        with self._lock:
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = {"rule": rule["name"],
                                         "series": series, "state": "ok",
                                         "since": now}
            was = st["state"]
            st.update(value=round(float(value), 6),
                      fast_burn=None if fast is None else round(fast, 4),
                      slow_burn=None if slow is None else round(slow, 4),
                      severity=rule.get("severity", "warn"), ts=now)
            if firing and was != "firing":
                st["state"] = "firing"
                st["since"] = now
                self.fired_total += 1
            elif not firing and was == "firing":
                st["state"] = "ok"
                st["since"] = now
            changed = st["state"] != was
            new_state = st["state"]
        if not changed:
            return
        tele = self.tele
        if new_state == "firing":
            tele.counter("alerts_fired").inc()
            tele.gauge("alert_firing_%s" % rule["name"]).set(1.0)
            tele.event("alert", rule=rule["name"], series=series,
                       state="firing", value=float(value),
                       fast_burn=fast, slow_burn=slow,
                       severity=rule.get("severity", "warn"))
            Log.warning("ALERT %s firing on %s (value=%.6g, burn "
                        "fast=%.3g slow=%.3g)", rule["name"], series,
                        value, fast, slow)
            if rule.get("capture", True):
                # the flight recorder decides whether anything happens
                # (armed, once per run, never recursive)
                from . import profiling
                profiling.on_incident("alert_%s" % rule["name"])
        else:
            tele.gauge("alert_firing_%s" % rule["name"]).set(0.0)
            tele.event("alert", rule=rule["name"], series=series,
                       state="resolved", value=float(value))
            Log.warning("ALERT %s resolved on %s", rule["name"], series)

    # ---- surfacing ----

    def note_external(self, name: str) -> None:
        """Fold an out-of-band incident (watchdog stall) into the fired
        tally so ``/alerts`` and the summary agree with the event
        stream."""
        with self._lock:
            self.fired_total += 1
            self.external[name] = self.external.get(name, 0) + 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            states = [dict(st) for _, st in sorted(self._state.items())]
            external = dict(self.external)
            fired = self.fired_total
        firing = sum(1 for st in states if st["state"] == "firing")
        return {"enabled": True, "interval_s": self.interval_s,
                "rules": len(self.rules), "series": states,
                "firing": firing, "fired_total": fired,
                **({"external": external} if external else {})}


def engine(tele) -> Optional[AlertEngine]:
    """The alert engine of run ``tele`` (None when none installed)."""
    return getattr(tele, "alerts", None) if tele is not None else None


def install(tele, rules_path: Optional[str] = None,
            rules: Optional[List[Dict[str, Any]]] = None,
            interval_s: float = DEFAULT_INTERVAL_S,
            start: bool = True) -> Optional[AlertEngine]:
    """Install (and by default start) an alert engine on the run; returns
    it, or None when the rules file is unreadable/empty (warned, never
    fatal — a missing rules file must not take training down)."""
    if tele is None:
        return None
    if rules is None:
        try:
            rules = load_rules(rules_path)
        except (OSError, ValueError, TypeError) as exc:
            Log.warning("alert_rules %r unreadable (%s); live alerting "
                        "disabled for this run", rules_path, exc)
            return None
    if not rules:
        Log.warning("alert_rules %r carries no usable rules; live "
                    "alerting disabled for this run", rules_path)
        return None
    eng = AlertEngine(tele, rules, interval_s=interval_s)
    tele.alerts = eng
    if start:
        eng.start()
    Log.info("alert engine armed: %d rule(s), eval every %.2gs",
             len(rules), eng.interval_s)
    return eng


def note_incident(tele, name: str, severity: str = "page",
                  **fields: Any) -> None:
    """Emit a firing ``kind="alert"`` event for an out-of-band incident
    (the watchdog calls this on a stall) and fold it into the engine's
    tally when one is installed.  Callers gate on ``tele is not None``."""
    tele.counter("alerts_fired").inc()
    tele.event("alert", rule=str(name), state="firing",
               severity=str(severity), **fields)
    eng = engine(tele)
    if eng is not None:
        eng.note_external(str(name))
