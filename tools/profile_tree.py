"""On-device profiling of the tree build / fused training chunk.

The counterpart of the reference's ``Timer``/``FunctionTimer`` aggregation
(include/LightGBM/utils/common.h:1032-1093) for DEVICE time: host-side timers
only see dispatch on an async runtime (the axon tunnel's block_until_ready is
unreliable), so this captures a ``jax.profiler`` trace and aggregates the XLA
op durations from the xplane protobuf directly (the
tensorboard_plugin_profile converter is broken against the installed
TF/protobuf pair).

Usage:
    python tools/profile_tree.py [rows] [leaves] [max_bin]   # tree build
    python tools/profile_tree.py --chunk [rows] [leaves]     # fused chunk

Captures through the ``lightgbm_tpu.obs.profiling`` layout (``--out``
root, default /tmp/lgbm_tpu_prof, one ``capture_<n>_profile_tree/`` dir
with a ``capture.json`` per invocation) — the SAME artifact shape the
triggered path (``/debug/profile``, the flight recorder) produces, so
this aggregation works on either.  Prints the top ops by total device
time, grouped by op name with counts — the numbers recorded in PERF.md.
"""
import collections
import glob
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def aggregate_xplane(trace_dir: str, top: int = 25):
    """[(name, total_ms, count)] by device time from the newest xplane.pb."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = sorted(glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True),
                   key=os.path.getmtime)
    if not paths:
        raise SystemExit("no xplane.pb under %s — did the profiler run?"
                         % trace_dir)
    xs = xplane_pb2.XSpace()
    with open(paths[-1], "rb") as fh:
        xs.ParseFromString(fh.read())
    plane = next((p for p in xs.planes if "TPU" in p.name), None)
    if plane is None:
        raise SystemExit("no TPU device plane in the trace (planes: %s) — "
                         "this tool needs a TPU backend"
                         % [p.name for p in xs.planes])
    ev_meta = plane.event_metadata
    agg = collections.Counter()
    cnt = collections.Counter()
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            key = re.sub(r"[.\d]+$", "", ev_meta[ev.metadata_id].name)
            agg[key] += ev.duration_ps
            cnt[key] += 1
    return [(name, t / 1e9, cnt[name]) for name, t in agg.most_common(top)]


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser(
        description="profile one tree build (or --chunk: a fused boosting "
                    "chunk) and aggregate device time from xplane")
    ap.add_argument("rows", nargs="?", type=int, default=1_000_000)
    ap.add_argument("leaves", nargs="?", type=int, default=255)
    ap.add_argument("max_bin", nargs="?", type=int, default=63)
    ap.add_argument("--chunk", action="store_true",
                    help="profile the fused train_chunk path instead")
    ap.add_argument("--nsrow", action="store_true",
                    help="also print per-op device time per logical "
                         "row-visit (PERF.md per-phase unit)")
    ap.add_argument("--out", default="/tmp/lgbm_tpu_prof",
                    help="capture root (obs/profiling layout: one "
                         "capture_<n>_profile_tree/ dir per invocation)")
    cli = ap.parse_args()
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    from lightgbm_tpu.utils.log import Log

    Log.reset_level(30)
    chunk = cli.chunk
    n = cli.rows
    leaves = cli.leaves
    max_bin = cli.max_bin

    rng = np.random.RandomState(0)
    X = rng.normal(size=(n, 28)).astype(np.float32)
    y = ((X[:, 0] * 2 + X[:, 1] ** 2 - X[:, 2] * X[:, 3]) > 0).astype(np.float64)
    ds = BinnedDataset.from_matrix(X, label=y, max_bin=max_bin)
    cfg = Config(objective="binary", num_leaves=leaves, max_bin=max_bin,
                 num_iterations=100)
    # the shared capture layout (obs/profiling.py): the standalone tool and
    # the triggered /debug/profile path produce identically-shaped
    # artifacts, so aggregate_xplane works on both
    from lightgbm_tpu.obs import profiling
    root = cli.out
    seq = len(glob.glob(os.path.join(root, "capture_*"))) + 1
    trace_dir = profiling.open_capture(root, seq, "profile_tree")

    if chunk:
        from lightgbm_tpu.boosting.gbdt import GBDT
        from lightgbm_tpu.objective import create_objective
        b = GBDT(cfg, ds, create_objective("binary", cfg))

        def sync():
            b.train_score.block_until_ready()
            float(jax.device_get(b.train_score[0, 0]))

        b.train_chunk(3)
        sync()
        t0 = time.perf_counter()
        b.train_chunk(3)
        sync()
        print("fused chunk: %.1f ms/iter" % ((time.perf_counter() - t0) / 3 * 1e3))
        with profiling.trace_block(trace_dir):
            b.train_chunk(3)
            sync()
        profiling.write_meta(trace_dir, reason="profile_tree",
                             mode="chunk", rows=n, leaves=leaves,
                             max_bin=max_bin)
    else:
        from lightgbm_tpu.core.tree_learner import SerialTreeLearner
        lrn = SerialTreeLearner(ds, cfg)
        g = jnp.asarray(rng.normal(size=n).astype(np.float32))
        h = jnp.asarray(rng.uniform(0.1, 1.0, size=n).astype(np.float32))
        arr = lrn.train(g, h, n)
        int(arr.num_leaves)
        t0 = time.perf_counter()
        for _ in range(3):
            arr = lrn.train(g, h, n)
        int(arr.num_leaves)
        print("tree build: %.1f ms" % ((time.perf_counter() - t0) / 3 * 1e3))
        with profiling.trace_block(trace_dir):
            arr = lrn.train(g, h, n)
            int(arr.num_leaves)
        profiling.write_meta(trace_dir, reason="profile_tree",
                             mode="tree", rows=n, leaves=leaves,
                             max_bin=max_bin)

    # --nsrow: also print each op's device time per LOGICAL row-visit, the
    # unit PERF.md's per-phase table uses.  Row-visits are exact from the
    # trained tree (every row passes one window per level) — the same
    # accounting bench.py uses for device_util.
    visits = None
    if cli.nsrow:
        if chunk:
            trees = b.models[-3:]
            visits = 0.0
            for t in trees:
                nl = t.num_leaves
                visits += float(np.sum(t.leaf_count[:nl] * t.leaf_depth[:nl]))
        else:
            nl = int(arr.num_leaves)
            visits = float(np.sum(np.asarray(arr.leaf_count)[:nl]
                                  * np.asarray(arr.leaf_depth)[:nl]))
    for name, ms, c in aggregate_xplane(trace_dir):
        if visits:
            print("%-74s %9.3f ms x%5d %8.3f ns/row-visit"
                  % (name[:72], ms, c, ms * 1e6 / visits))
        else:
            print("%-88s %9.3f ms x%5d" % (name[:86], ms, c))


if __name__ == "__main__":
    main()
