"""The empirical planner: microbenchmark candidate tilings, persist winners.

For each (shape-class, device_kind) the tuner:

1. enumerates **candidate plans** (:func:`candidate_plans`) — the analytic
   plan plus structured variations of the knobs PERF.md's
   "tried and rejected" table shows were hand-raced on v5e (bucket ladder
   shape: small kernel on/off, 1024- vs 4096-row chunks, mid-bucket
   bound; predict tree-block VMEM budget);
2. **measures** each candidate by running the REAL dispatches — a serial
   tree build with the candidate's ``bucket_plan`` pinned on the learner,
   and the blocked predict program at the candidate's tree-block G — with
   walls recorded into the compile-accounting machinery
   (:class:`~..obs.compile.CompileAccounting`): the first dispatch is a
   noted miss, repeats build the steady sample, and candidates are ranked
   on ``steady_p50_s`` so compiles and persistent-cache **warm loads
   never pollute the ranking** (obs/compile.py's whole reason to exist,
   per ROADMAP item 4);
3. **persists** the winner per shape-class into the atomic, versioned
   JSON plan cache (``plan/cache.py``) next to the XLA compilation cache.

Any candidate is numerics-safe: plans change dispatch shape only, and
every kernel variant is pinned bit-exact against the others — the tuner
races performance, never correctness.  Off-TPU the fused kernels run in
interpret mode (walls are mechanism-proof, not evidence; the BENCH
protocol runs this on hardware).

Driven by ``tools/bench_autotune.py``; tested with an injected timer in
tests/test_plan.py (ranking logic is deterministic under synthetic
walls).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from . import cache as _cache
from . import planner


class Candidate(NamedTuple):
    name: str
    plan: planner.Plan


def candidate_plans(sc: planner.ShapeClass) -> Tuple[Candidate, ...]:
    """The race field for one shape class: analytic first (the incumbent
    every winner's margin is quoted against), then the bucket-ladder and
    predict-block variations that are valid for this row count."""
    from ..core.partition import CHUNK, SMALL_CHUNK, _ALIGN, _MID_MAX
    base = planner.analytic_plan(sc)
    out: List[Candidate] = [Candidate("analytic", base)]
    n = sc.n_rows
    small_max = SMALL_CHUNK - _ALIGN

    def add(name: str, **fields) -> None:
        plan = base._replace(provenance="tuned", **fields)
        try:
            planner.validate_plan(plan, n)
        except ValueError:
            return  # variant invalid for this shape: not a candidate
        if any(c.plan[:-1] == plan[:-1] for c in out):
            return  # collapsed onto an existing candidate at this n
        out.append(Candidate(name, plan))

    def sched(name: str, bucket_plan) -> None:
        bucket_plan = tuple(bucket_plan)
        add(name, bucket_plan=bucket_plan, level_ladder=bucket_plan)

    # ladder variants (round-7 knobs): one-size large pipeline (the
    # round-6 status quo), one-size 1024-chunk pipeline, small kernel
    # disabled, and a mid bucket stretched to 2x its hand-tuned bound
    sched("single-large", ((False, CHUNK, None),))
    sched("single-mid", ((False, SMALL_CHUNK, None),))
    if small_max < n:
        no_small = [e for e in base.bucket_plan if not e[0]]
        if no_small:
            sched("no-small", no_small)
    if 2 * _MID_MAX < n:
        sched("wide-mid", ((True, SMALL_CHUNK, small_max),
                           (False, SMALL_CHUNK, 2 * _MID_MAX),
                           (False, CHUNK, None)))
    # round 22: quantized-gradient histograms halve the factored
    # accumulator per group, so the same VMEM gate admits doubled groups
    # and a wider mid/level window — raced as candidates, never assumed
    if getattr(sc, "quantized", False):
        add("quant-2xgroups", hist_groups=int(base.hist_groups) * 2)
        if 4 * _MID_MAX < n:
            sched("quant-wide-level", ((True, SMALL_CHUNK, small_max),
                                       (False, SMALL_CHUNK, 4 * _MID_MAX),
                                       (False, CHUNK, None)))
    # predict tree-block VMEM budget: half and double the 1 MiB default
    pb = int(base.predict_block_vmem_bytes)
    add("predict-halfvmem", predict_block_vmem_bytes=pb // 2)
    add("predict-2xvmem", predict_block_vmem_bytes=pb * 2)
    return tuple(out)


def _default_timer(fn) -> float:
    """Wall-seconds of one completed dispatch (device work drained)."""
    import jax
    t0 = time.perf_counter()
    jax.block_until_ready(fn())
    return time.perf_counter() - t0


def _steady_of(acct, fn: str, bucket: str) -> Optional[Dict[str, Any]]:
    snap = acct.snapshot()
    return (snap.get("keys") or {}).get("%s|%s" % (fn, bucket))


class TuneDriver:
    """Owns the synthetic workload of ONE shape class and measures
    candidates against it.  ``timer`` is injectable for tests."""

    def __init__(self, sc: planner.ShapeClass, *, reps: int = 4,
                 interpret: Optional[bool] = None, timer=None,
                 trees: int = 8, seed: int = 11) -> None:
        import jax
        self.sc = sc
        self.reps = max(2, int(reps))
        self.timer = timer or _default_timer
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        self.interpret = bool(interpret)
        self.trees = int(trees)
        self.seed = int(seed)
        self._fixture = None
        # one accountant per driver: keys are (site, candidate) so every
        # candidate's steady median lives beside its compile cost in the
        # artifact's candidate table
        from ..obs.compile import CompileAccounting
        self.acct = CompileAccounting()

    # ---- fixture: dataset + learner + a small trained model ----

    def _fixture_parts(self):
        if self._fixture is not None:
            return self._fixture
        import numpy as np

        from ..boosting.gbdt import GBDT
        from ..config import Config
        from ..core.partition import CHUNK
        from ..io.dataset import BinnedDataset
        from ..objective import create_objective

        sc = self.sc
        n = max(CHUNK, -(-sc.n_rows // CHUNK) * CHUNK)
        f = max(2, sc.num_features)
        max_bin = max(3, min(sc.num_bins - 1, 255))
        rng = np.random.RandomState(self.seed)
        X = rng.normal(size=(n, f)).astype(np.float32)
        y = (X[:, 0] * 1.5 + np.sin(X[:, 1])
             + rng.normal(scale=0.1, size=n))
        ds = BinnedDataset.from_matrix(X, label=y, max_bin=max_bin)
        cfg = Config(objective="regression", num_leaves=15,
                     num_iterations=self.trees, min_data_in_leaf=2,
                     verbosity=-1)
        booster = GBDT(cfg, ds, create_objective("regression", cfg))
        grad = rng.normal(size=n).astype(np.float32)
        hess = np.abs(rng.normal(size=n)).astype(np.float32) + 0.1
        self._fixture = (booster, grad, hess, X)
        return self._fixture

    def _trained_trees(self):
        booster, _, _, _ = self._fixture_parts()
        if booster.num_trees == 0:
            booster.train()
        return list(booster.models)

    # ---- per-candidate measurements ----

    def measure_train(self, cand: Candidate) -> Optional[Dict[str, Any]]:
        """One serial tree build per rep with the candidate's
        ``bucket_plan`` pinned on the learner — the composite the bucket
        schedule actually serves.  Key = ("train_tree", name)."""
        import jax.numpy as jnp
        booster, grad, hess, _ = self._fixture_parts()
        learner = booster.learner
        prev = (learner.bucket_plan, learner.use_pallas,
                learner.pallas_interpret)
        learner.bucket_plan = tuple(cand.plan.bucket_plan)
        learner.use_pallas = True
        learner.pallas_interpret = self.interpret
        g = jnp.asarray(grad)
        h = jnp.asarray(hess)
        n = int(grad.shape[0])
        try:
            for rep in range(self.reps + 1):
                wall = self.timer(lambda: learner.train(g, h, n))
                self.acct.note(None, "train_tree", cand.name, wall,
                               1 if rep == 0 else 0)
        finally:
            (learner.bucket_plan, learner.use_pallas,
             learner.pallas_interpret) = prev
        return _steady_of(self.acct, "train_tree", cand.name)

    def measure_predict(self, cand: Candidate) -> Optional[Dict[str, Any]]:
        """The blocked predict program at the candidate's tree-block G
        (pure XLA — measurable on any backend).  Key =
        ("predict_block", name)."""
        import jax.numpy as jnp

        from ..core.predict_fused import (predict_blocked, shape_bucket,
                                          stack_ensemble_blocked)
        trees = self._trained_trees()
        if not trees:
            return None
        _, _, _, X = self._fixture_parts()
        host_m = max(max(t.num_leaves - 1, 1) for t in trees)
        host_l = max(t.num_leaves for t in trees)
        g = planner.tree_block_for(cand.plan, len(trees), host_m, host_l)
        ens = stack_ensemble_blocked(trees, g)
        bucket = shape_bucket(min(len(X), cand.plan.predict_buckets[0]))
        rows = jnp.asarray(X[:bucket])
        for rep in range(self.reps + 1):
            wall = self.timer(lambda: predict_blocked(ens, rows))
            self.acct.note(None, "predict_block", cand.name, wall,
                           1 if rep == 0 else 0)
        return _steady_of(self.acct, "predict_block", cand.name)


def tune_shape(sc: planner.ShapeClass, *, reps: int = 4,
               interpret: Optional[bool] = None, timer=None,
               driver: Optional[TuneDriver] = None) -> Dict[str, Any]:
    """Race every candidate for one shape class; returns the candidate
    table + the merged winner (best bucket ladder x best predict block —
    the two site families are independent dispatches, so their winners
    compose)."""
    driver = driver or TuneDriver(sc, reps=reps, interpret=interpret,
                                  timer=timer)
    cands = candidate_plans(sc)
    table: List[Dict[str, Any]] = []
    for cand in cands:
        is_pred = cand.name.startswith("predict-")
        row: Dict[str, Any] = {
            "name": cand.name,
            "plan": planner.plan_to_dict(cand.plan),
        }
        if not is_pred:
            st = driver.measure_train(cand)
            if st:
                row["train_steady_p50_s"] = st.get("steady_p50_s")
                row["train_compile_s"] = st.get("compile_s")
        if is_pred or cand.name == "analytic":
            st = driver.measure_predict(cand)
            if st:
                row["predict_steady_p50_s"] = st.get("steady_p50_s")
                row["predict_compile_s"] = st.get("compile_s")
        table.append(row)

    def best(metric: str, rows) -> Optional[Dict[str, Any]]:
        scored = [r for r in rows if r.get(metric) is not None]
        return min(scored, key=lambda r: r[metric]) if scored else None

    base = next(r for r in table if r["name"] == "analytic")
    tb = best("train_steady_p50_s", table)
    pb = best("predict_steady_p50_s", table)
    winner = planner.plan_from_dict(base["plan"])
    parts = []
    margin: Dict[str, Any] = {}
    if tb is not None and tb["name"] != "analytic":
        w = planner.plan_from_dict(tb["plan"])
        winner = winner._replace(bucket_plan=w.bucket_plan,
                                 level_ladder=w.level_ladder)
        parts.append(tb["name"])
    if tb is not None and base.get("train_steady_p50_s"):
        margin["train"] = (float(base["train_steady_p50_s"])
                           / max(float(tb["train_steady_p50_s"]), 1e-12))
    if pb is not None and pb["name"] != "analytic":
        w = planner.plan_from_dict(pb["plan"])
        winner = winner._replace(
            predict_block_vmem_bytes=w.predict_block_vmem_bytes)
        parts.append(pb["name"])
    if pb is not None and base.get("predict_steady_p50_s"):
        margin["predict"] = (float(base["predict_steady_p50_s"])
                             / max(float(pb["predict_steady_p50_s"]), 1e-12))
    winner = winner._replace(provenance="tuned")
    planner.validate_plan(winner, sc.n_rows)
    return {
        "key": planner.plan_key(sc),
        "shape": list(sc),
        "candidates": table,
        "winner": {"name": "+".join(parts) or "analytic",
                   "plan": planner.plan_to_dict(winner)},
        "margin": margin,
    }


def run_sweep(shapes, *, cache_path: Optional[str] = None, reps: int = 4,
              interpret: Optional[bool] = None, timer=None,
              device_kind: Optional[str] = None,
              fixture_rows: Optional[int] = None, trees: int = 8,
              progress=None) -> Dict[str, Any]:
    """Tune every shape class, persist the winners, return the report
    ``tools/bench_autotune.py`` turns into the BENCH_autotune artifact.

    ``fixture_rows`` caps the synthetic workload's row count (off-TPU
    smoke runs) while the persisted entry stays keyed by the REQUESTED
    class — a capped fixture must not pollute a real class's key.
    ``progress`` is an optional ``fn(sc, res)`` callback per shape."""
    from . import device_specs
    if device_kind is None:
        device_kind = device_specs.current_device_kind()
    cache = _cache.PlanCache(device_kind=str(device_kind), path=cache_path)
    results = []
    for sc in shapes:
        sc = sc._replace(device_kind=str(device_kind))
        fx = sc.n_rows if fixture_rows is None else min(sc.n_rows,
                                                        int(fixture_rows))
        driver = TuneDriver(sc._replace(n_rows=fx), reps=reps,
                            interpret=interpret, timer=timer, trees=trees)
        res = tune_shape(sc._replace(n_rows=fx), driver=driver)
        res["key"] = planner.plan_key(sc)
        res["fixture_rows"] = fx
        cache.put(sc, planner.plan_from_dict(res["winner"]["plan"]),
                  metrics=res["margin"])
        results.append(res)
        if progress is not None:
            progress(sc, res)
    path = cache.save(cache_path) if results else None
    return {"device_kind": str(device_kind), "cache": path,
            "shapes": results}
