# RDS round-trip — role of the reference's saveRDS/readRDS.lgb.Booster.R:
# external-pointer handles do not survive serialization, so the model text
# is captured into the object before saveRDS and the handle is restored
# from it after readRDS.

#' @export
saveRDS.lgb.Booster <- function(object, file, ...) {
  payload <- list(model_str = lgb.model.to.string(object),
                  params = object$params,
                  best_iter = object$best_iter,
                  record_evals = object$record_evals)
  class(payload) <- "lgb.Booster.rds"
  saveRDS(payload, file, ...)
}

#' @export
readRDS.lgb.Booster <- function(file, ...) {
  payload <- readRDS(file, ...)
  stopifnot(inherits(payload, "lgb.Booster.rds"))
  bst <- lgb.load(model_str = payload$model_str)
  bst$params <- payload$params
  bst$best_iter <- payload$best_iter
  bst$record_evals <- payload$record_evals
  bst
}
