import os

# Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths are
# exercised without TPU hardware (the driver separately dry-runs multichip).
# Note: the env presets JAX_PLATFORMS=axon and the plugin overrides the env var,
# so the platform must be forced via jax.config after import.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
