"""Vectorized best-split search over feature histograms.

Counterpart of the reference ``FeatureHistogram::FindBestThreshold`` family
(src/treelearner/feature_histogram.hpp:84-304,440-680).  Where the reference scans
each feature's bins twice in serial loops (left->right and right->left to place the
missing-value default direction), this evaluates every (feature, threshold,
direction) candidate at once with prefix sums over the bin axis — the natural
formulation for the VPU, and one fused XLA program per leaf.

Semantics preserved from the reference:
- two directions only when the feature has a missing bin and >2 bins
  (feature_histogram.hpp:102-131); missing data implicitly follows the side that is
  computed as leaf_total - accumulated (":548,:614 skip default bin" trick);
- for MissingType.ZERO the default(zero) bin is excluded from both accumulations and
  its threshold position is not a candidate (:548,:614);
- for MissingType.NAN the last bin holds NaN and is excluded from the accumulated
  side (:542 ``use_na_as_missing``); with <=2 bins default_left=false (:128-130);
- bin counts estimated from hessians via ``cnt_factor = num_data/sum_hess``
  (:535,:601);
- gain math with L1 thresholding, L2, max_delta_step clamp (:463-527);
- validity: min_data_in_leaf / min_sum_hessian_in_leaf on both sides, gain strictly
  above parent gain + min_gain_to_split (:559-575); reported gain is the improvement
  (:114 ``output->gain -= min_gain_shift``);
- tie-breaking: the missing-left scan wins ties, larger thresholds win ties in the
  missing-left scan, smaller in the other (strict-``>`` update order of :579,:641),
  smaller feature index wins across features (split_info.hpp:185 comparators).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..io.binning import MissingType

K_EPSILON = 1e-15  # meta.h:51
K_MIN_SCORE = -jnp.inf


def dequantize_hist(hist: jax.Array, qscale: jax.Array) -> jax.Array:
    """Rescale an integer-valued quantized histogram back to real sums.

    ``hist[..., 2, B]`` holds per-bin integer sums (channel 0 = grad,
    channel 1 = hess) accumulated from quantized gradients; ``qscale`` is
    the ``(s_g, s_h)`` pair from ``quant.quantize_gradients``.  Works for
    any leading layout — ``[F, 2, B]``, ``[G, F, 2, B]``, or the
    psum_scatter-sharded ``[F/d, 2, B]`` — because the channel axis is
    always second-to-last.  Split-gain math downstream (this module) then
    runs on real-valued sums unchanged."""
    return hist * qscale.reshape((1,) * (hist.ndim - 2) + (2, 1))


class SplitParams(NamedTuple):
    """Static (trace-time) learner hyperparameters."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    max_delta_step: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    # categorical (config.h:600-640)
    max_cat_to_onehot: int = 4
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    min_data_per_group: int = 100
    # extremely-randomized trees (config.h:318): numerical features consider
    # ONE random threshold per (feature, leaf) instead of scanning every bin
    extra_trees: bool = False
    extra_seed: int = 6
    # per-feature split-gain scaling, inner-feature order (config.h:432-436:
    # gain[i] = max(0, feature_contri[i]) * gain[i]); () == disabled.  A
    # tuple so SplitParams stays hashable/static; learners index it by
    # GLOBAL inner feature id (see tree_learner's _apply_contri)
    feature_contri: tuple = ()


class FeatureInfo(NamedTuple):
    """Per-used-feature static metadata (device arrays, [F])."""
    num_bin: jax.Array       # i32
    missing_type: jax.Array  # i32 (MissingType)
    default_bin: jax.Array   # i32
    is_categorical: jax.Array  # bool
    monotone: jax.Array = None  # i32 in {-1, 0, +1}; None == unconstrained
    # EFB bundling (dataset.cpp:92-290): the binned matrix column of each
    # feature and its first group code; None == one column per feature
    group: jax.Array = None  # i32 [F] -> group column
    offset: jax.Array = None  # i32 [F] first group code of bin 1


class BestSplit(NamedTuple):
    """Per-leaf best split candidate (scalars + a [W] bin bitset for
    categorical many-vs-many splits; all-zero for numerical)."""
    gain: jax.Array          # improvement over parent (-inf if none)
    feature: jax.Array       # inner feature index, i32
    threshold: jax.Array     # bin threshold (left: bin <= threshold), i32
    default_left: jax.Array  # bool
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array    # f32 (estimated like the reference)
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array
    cat_bitset: jax.Array    # [B//32] u32; bins going LEFT (categorical only)


class FeatureBest(NamedTuple):
    """Best split of every feature (all [F] arrays) — the device analogue of the
    per-feature ``SplitInfo`` array the reference keeps per leaf
    (serial_tree_learner.cpp:399 best_split_per_leaf_); exposing it lets the
    parallel learners shard the scan (data_parallel_tree_learner.cpp:167) and vote
    on top-k features (voting_parallel_tree_learner.cpp:170)."""
    gain: jax.Array
    threshold: jax.Array
    default_left: jax.Array
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array
    left_count: jax.Array
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array
    cat_bitset: jax.Array    # [F, B//32] u32


def _avalanche_u32(x):
    """xxhash-style integer avalanche (the same mixer as gbdt._bag_uniforms,
    kept local to avoid a core -> boosting import)."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(2246822519)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(3266489917)
    return x ^ (x >> 16)


def _extra_trees_mask(feat: FeatureInfo, sum_grad, sum_hess, t,
                      params: SplitParams):
    """One random candidate threshold per (feature, leaf) — the reference's
    ``rand_threshold_gen_`` draw under ``extra_trees`` (config.h:318,
    feature_histogram.hpp use_rand_threshold).  The draw is a stateless hash
    of (extra_seed, feature index, leaf-total bits), so it is deterministic
    for a given dataset/seed yet varies across leaves and trees — a
    sequential RNG stream would not survive the vmapped per-leaf scan or
    the fused multi-iteration lax.scan."""
    f32 = jnp.float32
    salt = (jax.lax.bitcast_convert_type(
        sum_grad.astype(f32), jnp.int32).astype(jnp.uint32)
        ^ (jax.lax.bitcast_convert_type(
            sum_hess.astype(f32), jnp.int32).astype(jnp.uint32) << 1))
    F = feat.num_bin.shape[0]
    fid = jnp.arange(F, dtype=jnp.uint32)
    x = fid * jnp.uint32(2654435761)
    x = x ^ (salt + jnp.uint32(params.extra_seed & 0xFFFFFFFF)
             * jnp.uint32(0x9E3779B9))
    x = _avalanche_u32(x)
    # thresholds live in [0, nb - 2] (bin <= t goes left)
    ncand = jnp.maximum(feat.num_bin - 1, 1).astype(jnp.uint32)
    rbin = jax.lax.rem(x, ncand).astype(jnp.int32)
    return t == rbin[:, None]


def threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def calculate_leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step):
    ret = -threshold_l1(sum_grad, l1) / (sum_hess + l2)
    if max_delta_step > 0.0:
        ret = jnp.clip(ret, -max_delta_step, max_delta_step)
    return ret


def leaf_split_gain_given_output(sum_grad, sum_hess, l1, l2, output):
    sg_l1 = threshold_l1(sum_grad, l1)
    return -(2.0 * sg_l1 * output + (sum_hess + l2) * output * output)


def leaf_split_gain(sum_grad, sum_hess, l1, l2, max_delta_step):
    out = calculate_leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step)
    return leaf_split_gain_given_output(sum_grad, sum_hess, l1, l2, out)


def _split_gains(gl, hl, gr, hr, p: SplitParams):
    lo = calculate_leaf_output(gl, hl, p.lambda_l1, p.lambda_l2, p.max_delta_step)
    ro = calculate_leaf_output(gr, hr, p.lambda_l1, p.lambda_l2, p.max_delta_step)
    gain = (leaf_split_gain_given_output(gl, hl, p.lambda_l1, p.lambda_l2, lo)
            + leaf_split_gain_given_output(gr, hr, p.lambda_l1, p.lambda_l2, ro))
    return gain, lo, ro


def per_feature_best(hist: jax.Array, feat: FeatureInfo, feature_mask: jax.Array,
                     sum_grad: jax.Array, sum_hess: jax.Array,
                     num_data: jax.Array, params: SplitParams,
                     cmin=None, cmax=None,
                     threshold_mask=None) -> FeatureBest:
    """Best numerical split of EACH feature of one leaf (all outputs [F]).

    hist: [F, 2, B] f32; feature_mask: [F] bool (feature_fraction);
    sum_grad/sum_hess/num_data: leaf totals (scalars); cmin/cmax: the leaf's
    monotone-constraint bounds (monotone_constraints.hpp ConstraintEntry) —
    outputs are clamped into [cmin, cmax] and candidates on monotone features
    that violate the ordering are discarded (feature_histogram.hpp:468-527).
    ``threshold_mask`` [B] restricts the candidate thresholds — used to gather
    the stats of one FORCED threshold (feature_histogram.hpp:306
    GatherInfoForThreshold).
    """
    F, _, B = hist.shape
    g = hist[:, 0, :]
    h = hist[:, 1, :]
    total_h = sum_hess + 2 * K_EPSILON  # feature_histogram.hpp:88
    total_g = sum_grad
    num_data_f = num_data.astype(jnp.float32)
    cnt_factor = num_data_f / total_h
    c = jnp.round(h * cnt_factor)

    nb = feat.num_bin[:, None]                      # [F, 1]
    t = jnp.arange(B, dtype=jnp.int32)[None, :]     # [1, B] threshold candidates
    mt = feat.missing_type[:, None]
    is_def = t == feat.default_bin[:, None]
    is_nan_bin = t == nb - 1

    pre_g = jnp.cumsum(g, axis=1)
    pre_h = jnp.cumsum(h, axis=1)
    pre_c = jnp.cumsum(c, axis=1)
    g_nz = jnp.where(is_def, 0.0, g)
    h_nz = jnp.where(is_def, 0.0, h)
    c_nz = jnp.where(is_def, 0.0, c)
    pre_g_nz = jnp.cumsum(g_nz, axis=1)
    pre_h_nz = jnp.cumsum(h_nz, axis=1)
    pre_c_nz = jnp.cumsum(c_nz, axis=1)
    # totals over data bins only (padded bins hold zeros)
    tot = lambda a: a[:, -1:]
    # totals excluding the NaN bin (last data bin is nb-2)
    last_data = jnp.clip(nb - 2, 0, B - 1)
    at = lambda a, idx: jnp.take_along_axis(a, idx, axis=1)
    tot_nonan = lambda a: at(a, last_data)

    has_missing = (mt != int(MissingType.NONE)) & (nb > 2)
    is_nan_mode = mt == int(MissingType.NAN)
    is_zero_mode = mt == int(MissingType.ZERO)

    # ---------- direction 0: missing/default LEFT (reference dir=-1 scan) ----------
    right_g0 = jnp.where(has_missing & is_nan_mode, tot_nonan(pre_g) - pre_g,
                jnp.where(has_missing & is_zero_mode, tot(pre_g_nz) - pre_g_nz,
                          tot(pre_g) - pre_g))
    right_h0 = jnp.where(has_missing & is_nan_mode, tot_nonan(pre_h) - pre_h,
                jnp.where(has_missing & is_zero_mode, tot(pre_h_nz) - pre_h_nz,
                          tot(pre_h) - pre_h)) + K_EPSILON
    right_c0 = jnp.where(has_missing & is_nan_mode, tot_nonan(pre_c) - pre_c,
                jnp.where(has_missing & is_zero_mode, tot(pre_c_nz) - pre_c_nz,
                          tot(pre_c) - pre_c))
    left_g0 = total_g - right_g0
    left_h0 = total_h - right_h0
    left_c0 = num_data_f - right_c0
    # valid threshold range: t <= nb-2 always; t <= nb-3 when NaN two-dir;
    # zero-mode cannot place a threshold at default_bin - 1 (:548 skip -> t-1)
    valid0 = t <= nb - 2
    valid0 &= jnp.where(has_missing & is_nan_mode, t <= nb - 3, True)
    valid0 &= jnp.where(has_missing & is_zero_mode,
                        t != feat.default_bin[:, None] - 1, True)

    # ---------- direction 1: missing/default RIGHT (reference dir=+1 scan) --------
    left_g1 = jnp.where(is_zero_mode, pre_g_nz, pre_g)
    left_h1 = jnp.where(is_zero_mode, pre_h_nz, pre_h) + K_EPSILON
    left_c1 = jnp.where(is_zero_mode, pre_c_nz, pre_c)
    right_g1 = total_g - left_g1
    right_h1 = total_h - left_h1
    right_c1 = num_data_f - left_c1
    valid1 = has_missing & (t <= nb - 2)
    valid1 &= jnp.where(is_zero_mode, ~is_def, True)

    gain_shift = leaf_split_gain(total_g, total_h, params.lambda_l1,
                                 params.lambda_l2, params.max_delta_step)
    min_gain_shift = gain_shift + params.min_gain_to_split

    def evaluate(gl, hl, cl, gr, hr, cr, valid):
        ok = (valid
              & (cl >= params.min_data_in_leaf) & (cr >= params.min_data_in_leaf)
              & (hl >= params.min_sum_hessian_in_leaf)
              & (hr >= params.min_sum_hessian_in_leaf))
        gain, lo, ro = _split_gains_clamped(gl, hl, gr, hr, params,
                                            params.lambda_l2, cmin, cmax)
        if cmin is not None and feat.monotone is not None:
            mono = feat.monotone[:, None]
            ok &= ~(((mono > 0) & (lo > ro)) | ((mono < 0) & (lo < ro)))
        ok &= gain > min_gain_shift
        return jnp.where(ok, gain, K_MIN_SCORE), lo, ro

    if threshold_mask is not None:
        valid0 = valid0 & threshold_mask[None, :]
        valid1 = valid1 & threshold_mask[None, :]
    elif params.extra_trees:
        # forced splits (threshold_mask) bypass the randomization, matching
        # the reference's GatherInfoForThreshold
        et_mask = _extra_trees_mask(feat, sum_grad, sum_hess, t, params)
        valid0 = valid0 & et_mask
        valid1 = valid1 & et_mask
    gain0, lo0, ro0 = evaluate(left_g0, left_h0, left_c0,
                               right_g0, right_h0, right_c0, valid0)
    gain1, lo1, ro1 = evaluate(left_g1, left_h1, left_c1,
                               right_g1, right_h1, right_c1, valid1)

    fm = feature_mask & ~feat.is_categorical
    gain0 = jnp.where(fm[:, None], gain0, K_MIN_SCORE)
    gain1 = jnp.where(fm[:, None], gain1, K_MIN_SCORE)

    # per-feature argmax with reference tie-breaking
    idx0 = (B - 1) - jnp.argmax(gain0[:, ::-1], axis=1)   # largest t wins ties
    best0 = jnp.take_along_axis(gain0, idx0[:, None], axis=1)[:, 0]
    idx1 = jnp.argmax(gain1, axis=1)                      # smallest t wins ties
    best1 = jnp.take_along_axis(gain1, idx1[:, None], axis=1)[:, 0]
    use1 = best1 > best0                                  # dir0 wins ties
    feat_gain = jnp.where(use1, best1, best0)
    feat_thr = jnp.where(use1, idx1, idx0).astype(jnp.int32)

    # with <=2 bins and NaN missing, the single scan reports default_left = false
    # (feature_histogram.hpp:128-130)
    two_bin_nan = (mt[:, 0] == int(MissingType.NAN)) & (feat.num_bin <= 2)
    feat_default_left = ~use1 & ~two_bin_nan

    fidx = jnp.arange(F)

    def pick(arr0, arr1):
        return jnp.where(use1, arr1[fidx, feat_thr], arr0[fidx, feat_thr])

    found = feat_gain > K_MIN_SCORE
    return FeatureBest(
        gain=jnp.where(found, feat_gain - min_gain_shift, K_MIN_SCORE),
        threshold=feat_thr,
        default_left=feat_default_left,
        left_sum_grad=pick(left_g0, left_g1),
        left_sum_hess=pick(left_h0, left_h1) - K_EPSILON,
        left_count=pick(left_c0, left_c1),
        right_sum_grad=pick(right_g0, right_g1),
        right_sum_hess=pick(right_h0, right_h1) - K_EPSILON,
        right_count=pick(right_c0, right_c1),
        left_output=jnp.where(use1, lo1[fidx, feat_thr], lo0[fidx, feat_thr]),
        right_output=jnp.where(use1, ro1[fidx, feat_thr], ro0[fidx, feat_thr]),
        cat_bitset=jnp.zeros((F, B // 32), dtype=jnp.uint32),
    )


def _bits_to_words(bits: jax.Array) -> jax.Array:
    """[..., B] bool -> [..., B//32] u32 bitset words."""
    shape = bits.shape[:-1]
    B = bits.shape[-1]
    w = bits.reshape(shape + (B // 32, 32)).astype(jnp.uint32)
    return (w << jnp.arange(32, dtype=jnp.uint32)).sum(axis=-1, dtype=jnp.uint32)


def per_feature_best_categorical(hist: jax.Array, feat: FeatureInfo,
                                 feature_mask: jax.Array, sum_grad: jax.Array,
                                 sum_hess: jax.Array, num_data: jax.Array,
                                 params: SplitParams,
                                 cmin=None, cmax=None) -> FeatureBest:
    """Best categorical split of each feature
    (feature_histogram.hpp:136-304 FindBestThresholdCategorical).

    One-hot mode for features with <= max_cat_to_onehot bins; otherwise the
    sorted many-vs-many scan: bins with count >= cat_smooth sorted by
    grad/(hess+cat_smooth), prefix-scanned from both ends up to
    max_cat_threshold with the min_data_per_group batching.  The serial
    two-direction scan becomes a vmapped lax.scan over the (small) bin axis.
    Resulting left-bin sets are returned as bitsets."""
    F, _, B = hist.shape
    W = B // 32
    p = params
    g = hist[:, 0, :]
    h = hist[:, 1, :]
    total_h = sum_hess + 2 * K_EPSILON
    total_g = sum_grad
    num_data_f = num_data.astype(jnp.float32)
    cnt_factor = num_data_f / total_h
    cnt = jnp.round(h * cnt_factor)

    is_full = feat.missing_type == int(MissingType.NONE)
    used_bin = feat.num_bin - 1 + is_full.astype(jnp.int32)     # [F]
    t = jnp.arange(B, dtype=jnp.int32)[None, :]
    in_range = t < used_bin[:, None]

    gain_shift = leaf_split_gain(total_g, total_h, p.lambda_l1, p.lambda_l2,
                                 p.max_delta_step)
    min_gain_shift = gain_shift + p.min_gain_to_split
    use_onehot = feat.num_bin <= p.max_cat_to_onehot                # [F]

    # ---------- one-hot: category t vs rest (:157-189) ----------
    other_g = total_g - g
    other_h = total_h - h - K_EPSILON
    other_cnt = num_data_f - cnt
    ok1 = (in_range & (cnt >= p.min_data_in_leaf)
           & (h >= p.min_sum_hessian_in_leaf)
           & (other_cnt >= p.min_data_in_leaf)
           & (other_h >= p.min_sum_hessian_in_leaf))
    oh_gain, oh_lo, oh_ro = _split_gains_clamped(
        g, h + K_EPSILON, other_g, other_h, p, p.lambda_l2, cmin, cmax)
    oh_gain = jnp.where(ok1 & (oh_gain > min_gain_shift), oh_gain, K_MIN_SCORE)
    oh_t = jnp.argmax(oh_gain, axis=1).astype(jnp.int32)            # first max
    fidx = jnp.arange(F)
    oh_best = oh_gain[fidx, oh_t]

    # ---------- sorted many-vs-many (:191-268) ----------
    l2c = p.lambda_l2 + p.cat_l2
    valid_sort = in_range & (cnt >= p.cat_smooth)
    ctr = g / (h + p.cat_smooth)
    sort_key = jnp.where(valid_sort, ctr, jnp.inf)
    order = jnp.argsort(sort_key, axis=1, stable=True).astype(jnp.int32)
    used = valid_sort.sum(axis=1).astype(jnp.int32)                 # [F]
    max_num_cat = jnp.minimum(p.max_cat_threshold, (used + 1) // 2)

    gs = jnp.take_along_axis(g, order, axis=1)
    hs = jnp.take_along_axis(h, order, axis=1)
    cs = jnp.take_along_axis(cnt, order, axis=1)

    def scan_dir(gs_f, hs_f, cs_f, used_f, maxcat_f, backward):
        def idx(i):
            return jnp.where(backward, jnp.maximum(used_f - 1 - i, 0), i)

        def step(state, i):
            sum_lg, sum_lh, left_c, cnt_grp, stop, bgain, bi = state
            j = idx(i)
            active = (i < used_f) & (i < maxcat_f) & ~stop
            af = active.astype(jnp.float32)
            sum_lg = sum_lg + gs_f[j] * af
            sum_lh = sum_lh + hs_f[j] * af
            left_c = left_c + cs_f[j] * af
            cnt_grp = cnt_grp + cs_f[j] * af
            cont1 = ((left_c < p.min_data_in_leaf)
                     | (sum_lh < p.min_sum_hessian_in_leaf))
            right_c = num_data_f - left_c
            sum_rh = total_h - sum_lh
            brk = ((right_c < p.min_data_in_leaf)
                   | (right_c < p.min_data_per_group)
                   | (sum_rh < p.min_sum_hessian_in_leaf))
            reached_group = active & ~cont1 & ~brk & \
                (cnt_grp >= p.min_data_per_group)
            sum_rg = total_g - sum_lg
            gain, _, _ = _split_gains_clamped(sum_lg, sum_lh, sum_rg, sum_rh,
                                              p, l2c, cmin, cmax)
            cand = reached_group & (gain > min_gain_shift) & (gain > bgain)
            bgain = jnp.where(cand, gain, bgain)
            bi = jnp.where(cand, i, bi)
            cnt_grp = jnp.where(reached_group, 0.0, cnt_grp)
            stop = stop | (active & brk)
            return (sum_lg, sum_lh, left_c, cnt_grp, stop, bgain, bi), None

        init = (jnp.float32(0), jnp.float32(K_EPSILON), jnp.float32(0),
                jnp.float32(0), jnp.bool_(False), jnp.float32(K_MIN_SCORE),
                jnp.int32(-1))
        (slg, slh, lc, cg, st, bgain, bi), _ = jax.lax.scan(
            step, init, jnp.arange(B, dtype=jnp.int32))
        return bgain, bi

    vscan = jax.vmap(scan_dir, in_axes=(0, 0, 0, 0, 0, None))
    fwd_gain, fwd_i = vscan(gs, hs, cs, used, max_num_cat, False)
    bwd_gain, bwd_i = vscan(gs, hs, cs, used, max_num_cat, True)
    use_bwd = bwd_gain > fwd_gain                                    # fwd ties
    so_gain = jnp.where(use_bwd, bwd_gain, fwd_gain)
    so_i = jnp.where(use_bwd, bwd_i, fwd_i)

    # recompute left sums at the winning prefix (inclusive of position so_i)
    pos = jnp.arange(B, dtype=jnp.int32)[None, :]
    in_prefix = jnp.where(use_bwd[:, None],
                          (pos >= jnp.maximum(used - 1 - so_i, 0)[:, None])
                          & (pos < used[:, None]),
                          pos <= so_i[:, None])
    in_prefix &= so_i[:, None] >= 0
    so_lg = jnp.sum(jnp.where(in_prefix, gs, 0.0), axis=1)
    so_lh = jnp.sum(jnp.where(in_prefix, hs, 0.0), axis=1) + K_EPSILON
    so_lc = jnp.sum(jnp.where(in_prefix, cs, 0.0), axis=1)

    # ---------- combine one-hot / sorted per feature ----------
    oh = use_onehot
    cat_gain = jnp.where(oh, oh_best, so_gain)
    l_g = jnp.where(oh, g[fidx, oh_t], so_lg)
    l_h = jnp.where(oh, h[fidx, oh_t] + K_EPSILON, so_lh)
    l_c = jnp.where(oh, cnt[fidx, oh_t], so_lc)
    eff_l2 = jnp.where(oh, p.lambda_l2, l2c)
    r_g = total_g - l_g
    r_h = total_h - l_h
    r_c = num_data_f - l_c
    l_out = _leaf_output_l2(l_g, l_h, p, eff_l2)
    r_out = _leaf_output_l2(r_g, r_h, p, eff_l2)
    if cmin is not None:
        l_out = jnp.clip(l_out, cmin, cmax)
        r_out = jnp.clip(r_out, cmin, cmax)

    # left-bin bitsets: one-hot -> {oh_t}; sorted -> prefix through order
    bits_oh = t == oh_t[:, None]
    bits_sorted = jnp.zeros((F, B), dtype=bool)
    scatter_f = jnp.broadcast_to(fidx[:, None], (F, B)).reshape(-1)
    bits_sorted = bits_sorted.at[scatter_f, order.reshape(-1)].set(
        in_prefix.reshape(-1))
    bits = jnp.where(oh[:, None], bits_oh, bits_sorted)

    found = (cat_gain > K_MIN_SCORE) & feature_mask & feat.is_categorical
    zero = jnp.zeros((F,), jnp.float32)
    return FeatureBest(
        gain=jnp.where(found, cat_gain - min_gain_shift, K_MIN_SCORE),
        threshold=jnp.where(oh, oh_t, so_i + 1).astype(jnp.int32),
        default_left=jnp.zeros((F,), bool),
        left_sum_grad=jnp.where(found, l_g, zero),
        left_sum_hess=jnp.where(found, l_h - K_EPSILON, zero),
        left_count=jnp.where(found, l_c, zero),
        right_sum_grad=jnp.where(found, r_g, zero),
        right_sum_hess=jnp.where(found, r_h - K_EPSILON, zero),
        right_count=jnp.where(found, r_c, zero),
        left_output=l_out,
        right_output=r_out,
        cat_bitset=jnp.where(found[:, None], _bits_to_words(bits), 0).astype(
            jnp.uint32),
    )


def _split_gains_l2(gl, hl, gr, hr, p: SplitParams, l2):
    lo = _leaf_output_l2(gl, hl, p, l2)
    ro = _leaf_output_l2(gr, hr, p, l2)
    gain = (leaf_split_gain_given_output(gl, hl, p.lambda_l1, l2, lo)
            + leaf_split_gain_given_output(gr, hr, p.lambda_l1, l2, ro))
    return gain, lo, ro


def _split_gains_clamped(gl, hl, gr, hr, p: SplitParams, l2, cmin, cmax):
    """Like _split_gains_l2, but candidate outputs are clamped into the leaf's
    monotone bounds BEFORE computing gain, matching GetSplitGains going through
    ConstraintEntry (feature_histogram.hpp:468-527) so candidate ranking under
    monotone constraints agrees with the reference."""
    lo = _leaf_output_l2(gl, hl, p, l2)
    ro = _leaf_output_l2(gr, hr, p, l2)
    if cmin is not None:
        lo = jnp.clip(lo, cmin, cmax)
        ro = jnp.clip(ro, cmin, cmax)
    gain = (leaf_split_gain_given_output(gl, hl, p.lambda_l1, l2, lo)
            + leaf_split_gain_given_output(gr, hr, p.lambda_l1, l2, ro))
    return gain, lo, ro


def _leaf_output_l2(sum_grad, sum_hess, p: SplitParams, l2):
    ret = -threshold_l1(sum_grad, p.lambda_l1) / (sum_hess + l2)
    if p.max_delta_step > 0.0:
        ret = jnp.clip(ret, -p.max_delta_step, p.max_delta_step)
    return ret


def per_feature_best_combined(hist: jax.Array, feat: FeatureInfo,
                              feature_mask: jax.Array, sum_grad: jax.Array,
                              sum_hess: jax.Array, num_data: jax.Array,
                              params: SplitParams,
                              any_categorical: bool = True,
                              cmin=None, cmax=None) -> FeatureBest:
    """Numerical + categorical per-feature bests merged by feature type."""
    fb_num = per_feature_best(hist, feat, feature_mask, sum_grad, sum_hess,
                              num_data, params, cmin, cmax)
    if not any_categorical:
        return fb_num
    fb_cat = per_feature_best_categorical(hist, feat, feature_mask, sum_grad,
                                          sum_hess, num_data, params,
                                          cmin, cmax)
    is_cat = feat.is_categorical
    merged = [jnp.where(is_cat[(...,) + (None,) * (c.ndim - 1)], c, n)
              if c.ndim > 1 else jnp.where(is_cat, c, n)
              for n, c in zip(fb_num, fb_cat)]
    return FeatureBest(*merged)


def reduce_feature_best(fb: FeatureBest, feature_ids: jax.Array) -> BestSplit:
    """Argmax-by-gain across features; ties go to the smaller feature id
    (split_info.hpp:185 comparators).  ``feature_ids`` maps positions in ``fb`` to
    global inner-feature indices (they must be ascending for the tie-break)."""
    best_f = jnp.argmax(fb.gain).astype(jnp.int32)   # first max = smallest id
    return BestSplit(
        gain=fb.gain[best_f],
        feature=feature_ids[best_f].astype(jnp.int32),
        threshold=fb.threshold[best_f],
        default_left=fb.default_left[best_f],
        left_sum_grad=fb.left_sum_grad[best_f],
        left_sum_hess=fb.left_sum_hess[best_f],
        left_count=fb.left_count[best_f],
        right_sum_grad=fb.right_sum_grad[best_f],
        right_sum_hess=fb.right_sum_hess[best_f],
        right_count=fb.right_count[best_f],
        left_output=fb.left_output[best_f],
        right_output=fb.right_output[best_f],
        cat_bitset=fb.cat_bitset[best_f],
    )


def sync_best(best: BestSplit, axis_name: str) -> BestSplit:
    """Allreduce-argmax of per-shard best splits across a mesh axis — the XLA
    equivalent of ``SyncUpGlobalBestSplit`` (parallel_tree_learner.h:190-213):
    all_gather the candidates and pick max gain, ties to the smaller feature id."""
    g = BestSplit(*[jax.lax.all_gather(x, axis_name) for x in best])  # [d] each
    max_gain = jnp.max(g.gain)
    tie_feat = jnp.where(g.gain == max_gain, g.feature, jnp.int32(2**31 - 1))
    i = jnp.argmin(tie_feat)
    return BestSplit(*[x[i] for x in g])


@functools.partial(jax.jit, static_argnames=("params",))
def best_split_numerical(hist: jax.Array, feat: FeatureInfo, feature_mask: jax.Array,
                         sum_grad: jax.Array, sum_hess: jax.Array,
                         num_data: jax.Array, params: SplitParams) -> BestSplit:
    """Best numerical split over all features of one leaf (scalars out)."""
    fb = per_feature_best(hist, feat, feature_mask, sum_grad, sum_hess,
                          num_data, params)
    return reduce_feature_best(fb, jnp.arange(hist.shape[0], dtype=jnp.int32))
