"""Objective factory (src/objective/objective_function.cpp:16-52)."""
from __future__ import annotations

from .base import ObjectiveFunction
from .binary import BinaryLogloss
from .multiclass import MulticlassOVA, MulticlassSoftmax
from .rank import LambdarankNDCG, RankXENDCG
from .regression import (RegressionFairLoss, RegressionGammaLoss,
                         RegressionHuberLoss, RegressionL1Loss,
                         RegressionL2Loss, RegressionMAPELoss,
                         RegressionPoissonLoss, RegressionQuantileLoss,
                         RegressionTweedieLoss)
from .xentropy import CrossEntropy, CrossEntropyLambda
from ..utils.log import Log

_OBJECTIVES = {
    "regression": RegressionL2Loss,
    "regression_l1": RegressionL1Loss,
    "quantile": RegressionQuantileLoss,
    "huber": RegressionHuberLoss,
    "fair": RegressionFairLoss,
    "poisson": RegressionPoissonLoss,
    "binary": BinaryLogloss,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "mape": RegressionMAPELoss,
    "gamma": RegressionGammaLoss,
    "tweedie": RegressionTweedieLoss,
}


def create_objective(name: str, config) -> ObjectiveFunction | None:
    if name == "custom":
        return None
    cls = _OBJECTIVES.get(name)
    if cls is None:
        Log.fatal("Unknown objective type name: %s", name)
    return cls(config)


__all__ = ["ObjectiveFunction", "create_objective"] + [
    c.__name__ for c in _OBJECTIVES.values()]
