"""Learning-to-rank objectives: lambdarank NDCG and rank_xendcg.

Counterparts of src/objective/rank_objective.hpp:23-202 (LambdarankNDCG) and
src/objective/rank_xendcg_objective.hpp:25-110 (RankXENDCG).

TPU-first design: queries are bucketed by padded size (powers of two) at init;
each bucket is a [Q, S] gather of scores through a static index matrix, the
per-query pairwise lambda computation runs as one jitted [Q, S, S] tensor
kernel per bucket, and results scatter-add back into the [N] gradient vector —
no host round-trip per iteration (the reference's per-query OpenMP loops,
rank_objective.hpp:117-168, become batched device math).  Exact sigmoids are
used instead of the reference's lookup table (:185-200) — the table is a CPU
speed hack, not semantics.
"""
from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction
from ..metric.dcg import DCGCalculator
from ..utils.log import Log

# cap on per-bucket [Q, S, S] pair-tensor elements (memory guard)
_PAIR_BUDGET = 1 << 26


def _make_buckets(query_boundaries: np.ndarray, num_data: int
                  ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Group queries by padded size: [(idx [Q, S] with num_data padding,
    qids [Q]), ...] for S in powers of two."""
    lens = np.diff(query_boundaries)
    out = []
    sizes = {}
    for q, cnt in enumerate(lens):
        s = 8
        while s < cnt:
            s *= 2
        sizes.setdefault(s, []).append(q)
    for s, qids in sorted(sizes.items()):
        idx = np.full((len(qids), s), num_data, dtype=np.int32)
        for r, q in enumerate(qids):
            lo, hi = query_boundaries[q], query_boundaries[q + 1]
            idx[r, :hi - lo] = np.arange(lo, hi, dtype=np.int32)
        out.append((idx, np.asarray(qids, dtype=np.int32)))
    return out


@functools.partial(jax.jit, static_argnames=("sigmoid", "norm"))
def _lambdarank_bucket(scores: jax.Array, labels: jax.Array, mask: jax.Array,
                       inv_max_dcg: jax.Array, label_gain: jax.Array,
                       discounts: jax.Array, *, sigmoid: float, norm: bool):
    """Pairwise lambdas for one size bucket.

    scores/labels/mask: [Q, S] (pad rows masked); returns (lambda, hess) [Q, S]
    in the bucket's (unsorted) doc order.  Mirrors
    LambdarankNDCG::GetGradientsForOneQuery (rank_objective.hpp:117-168).
    """
    q, s_dim = scores.shape
    neg = jnp.where(mask, scores, -jnp.inf)
    order = jnp.argsort(-neg, axis=1, stable=True)
    s = jnp.take_along_axis(scores, order, axis=1)
    m = jnp.take_along_axis(mask, order, axis=1)
    lab = jnp.take_along_axis(labels, order, axis=1)
    gains = label_gain[jnp.clip(lab, 0, label_gain.shape[0] - 1)]
    disc = discounts[:s_dim][None, :]
    cnt = jnp.sum(mask, axis=1).astype(jnp.int32)
    best = s[:, 0]
    worst = jnp.take_along_axis(
        s, jnp.maximum(cnt - 1, 0)[:, None], axis=1)[:, 0]

    valid = ((lab[:, :, None] > lab[:, None, :])
             & m[:, :, None] & m[:, None, :])
    ds = jnp.where(valid, s[:, :, None] - s[:, None, :], 0.0)
    dndcg = (jnp.abs(gains[:, :, None] - gains[:, None, :])
             * jnp.abs(disc[:, :, None] - disc[:, None, :])
             * inv_max_dcg[:, None, None])
    if norm:
        same = (best == worst)[:, None, None]
        dndcg = jnp.where(same, dndcg, dndcg / (0.01 + jnp.abs(ds)))
    p = 1.0 / (1.0 + jnp.exp(sigmoid * ds))
    p_lambda = jnp.where(valid, -sigmoid * dndcg * p, 0.0)
    p_hess = jnp.where(valid, sigmoid * sigmoid * dndcg * p * (1.0 - p), 0.0)
    lam = jnp.sum(p_lambda, axis=2) - jnp.sum(p_lambda, axis=1)
    hes = jnp.sum(p_hess, axis=2) + jnp.sum(p_hess, axis=1)
    if norm:
        sum_lambdas = -2.0 * jnp.sum(p_lambda, axis=(1, 2))
        nf = jnp.where(sum_lambdas > 0,
                       jnp.log2(1.0 + sum_lambdas)
                       / jnp.maximum(sum_lambdas, 1e-300), 1.0)
        lam = lam * nf[:, None]
        hes = hes * nf[:, None]
    # unsort back to the bucket's doc positions
    inv = jnp.argsort(order, axis=1)
    return (jnp.take_along_axis(lam, inv, axis=1),
            jnp.take_along_axis(hes, inv, axis=1))


class LambdarankNDCG(ObjectiveFunction):
    name = "lambdarank"
    need_accurate_prediction = False

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            Log.fatal("Sigmoid param %f should be greater than zero", self.sigmoid)
        self.norm = bool(config.lambdamart_norm)
        self.optimize_pos_at = int(config.max_position)
        DCGCalculator.init(list(config.label_gain) or None)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("Lambdarank tasks require query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries)
        DCGCalculator.check_label(self.label_np)
        inverse_max_dcgs = np.zeros(len(self.query_boundaries) - 1)
        for q in range(len(inverse_max_dcgs)):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            maxdcg = DCGCalculator.cal_max_dcg_at_k(self.optimize_pos_at,
                                                    self.label_np[lo:hi])
            inverse_max_dcgs[q] = 1.0 / maxdcg if maxdcg > 0 else 0.0
        # device bucket structures
        self._buckets = []
        label_pad = np.concatenate([self.label_np.astype(np.int32), [0]])
        max_s = 8
        for idx, qids in _make_buckets(self.query_boundaries, num_data):
            s = idx.shape[1]
            max_s = max(max_s, s)
            chunk = max(_PAIR_BUDGET // (s * s), 1)
            for lo in range(0, idx.shape[0], chunk):
                part_idx = idx[lo:lo + chunk]
                self._buckets.append({
                    "idx": jnp.asarray(part_idx),
                    "labels": jnp.asarray(label_pad[part_idx]),
                    "mask": jnp.asarray(part_idx < num_data),
                    "inv_max_dcg": jnp.asarray(
                        inverse_max_dcgs[qids[lo:lo + chunk]].astype(
                            np.float32)),
                })
        self._label_gain = jnp.asarray(
            np.asarray(DCGCalculator.label_gain_, dtype=np.float32))
        disc = np.asarray(DCGCalculator.discount_, dtype=np.float32)
        if max_s > disc.shape[0]:   # queries beyond kMaxPosition positions
            disc = np.concatenate(
                [disc, np.full(max_s - disc.shape[0], disc[-1], np.float32)])
        self._discounts = jnp.asarray(disc[:max_s])

    def get_gradients(self, score):
        score = jnp.asarray(score, dtype=jnp.float32).reshape(-1)
        score_pad = jnp.concatenate([score, jnp.zeros((1,), jnp.float32)])
        lam = jnp.zeros((self.num_data,), jnp.float32)
        hes = jnp.zeros((self.num_data,), jnp.float32)
        for b in self._buckets:
            sc = score_pad[b["idx"]]
            bl, bh = _lambdarank_bucket(sc, b["labels"], b["mask"],
                                        b["inv_max_dcg"], self._label_gain,
                                        self._discounts,
                                        sigmoid=self.sigmoid, norm=self.norm)
            lam = lam.at[b["idx"].reshape(-1)].add(bl.reshape(-1),
                                                   mode="drop")
            hes = hes.at[b["idx"].reshape(-1)].add(bh.reshape(-1),
                                                   mode="drop")
        if self.weights is not None:
            lam = lam * self.weights
            hes = hes * self.weights
        return lam, hes

    def to_string(self):
        return self.name


@jax.jit
def _xendcg_bucket(scores: jax.Array, labels: jax.Array, mask: jax.Array,
                   gammas: jax.Array):
    """Listwise XE-NDCG lambdas for one bucket ([Q, S] rows; pads masked).
    Mirrors RankXENDCG::GetGradientsForOneQuery
    (rank_xendcg_objective.hpp:43-110)."""
    neg_inf = jnp.float32(-1e30)
    sm = jnp.where(mask, scores, neg_inf)
    e = jnp.exp(sm - jnp.max(sm, axis=1, keepdims=True))
    rho = e / jnp.sum(e, axis=1, keepdims=True)
    phi = jnp.where(mask, jnp.power(2.0, labels.astype(jnp.float32)) - gammas,
                    0.0)
    sum_labels = jnp.sum(phi, axis=1, keepdims=True)
    ok = jnp.abs(sum_labels) > 1e-15
    l1 = jnp.where(mask, -phi / jnp.where(ok, sum_labels, 1.0) + rho, 0.0)
    inv = jnp.where(mask, 1.0 / jnp.maximum(1.0 - rho, 1e-15), 0.0)
    li = l1 * inv
    l2 = jnp.sum(li, axis=1, keepdims=True) - li
    rl = rho * l2 * inv
    l3 = jnp.sum(rl, axis=1, keepdims=True) - rl
    lam = jnp.where(mask & ok, l1 + rho * l2 + rho * l3, 0.0)
    hes = jnp.where(mask & ok, rho * (1.0 - rho), 0.0)
    cnt = jnp.sum(mask, axis=1, keepdims=True)
    single = cnt <= 1
    return jnp.where(single, 0.0, lam), jnp.where(single, 0.0, hes)


class RankXENDCG(ObjectiveFunction):
    """Listwise cross-entropy NDCG surrogate (rank_xendcg_objective.hpp:25-110):
    phi(l, gamma) = 2^l - gamma with per-doc uniform gammas, batched on device."""
    name = "rank_xendcg"
    need_accurate_prediction = False
    deterministic_gradients = False  # fresh gammas every call

    def __init__(self, config):
        super().__init__(config)
        self._seed = int(getattr(config, "objective_seed", 5))
        self._call = 0

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("RankXENDCG tasks require query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries)
        label_pad = np.concatenate([self.label_np.astype(np.float32), [0.0]])
        self._buckets = []
        for idx, _ in _make_buckets(self.query_boundaries, num_data):
            self._buckets.append({
                "idx": jnp.asarray(idx),
                "labels": jnp.asarray(label_pad[idx]),
                "mask": jnp.asarray(idx < num_data),
            })

    def get_gradients(self, score):
        score = jnp.asarray(score, dtype=jnp.float32).reshape(-1)
        score_pad = jnp.concatenate([score, jnp.zeros((1,), jnp.float32)])
        lam = jnp.zeros((self.num_data,), jnp.float32)
        hes = jnp.zeros((self.num_data,), jnp.float32)
        self._call += 1
        key = jax.random.PRNGKey(self._seed + self._call)
        for i, b in enumerate(self._buckets):
            sc = score_pad[b["idx"]]
            gammas = jax.random.uniform(jax.random.fold_in(key, i),
                                        b["idx"].shape, dtype=jnp.float32)
            bl, bh = _xendcg_bucket(sc, b["labels"], b["mask"], gammas)
            lam = lam.at[b["idx"].reshape(-1)].add(bl.reshape(-1), mode="drop")
            hes = hes.at[b["idx"].reshape(-1)].add(bh.reshape(-1), mode="drop")
        return lam, hes
