"""Fused split pass: routing + stable partition + child histogram in ONE
Pallas kernel invocation per split.

Counterpart of the reference's per-split trio — ``DataPartition::Split``
(src/treelearner/data_partition.hpp:113), the ordered-index histogram
(src/io/dense_bin.hpp:48 ConstructHistogram over begin..end), and the GPU
learner's copy/kernel overlap (src/treelearner/gpu_tree_learner.cpp:952-1055)
— rebuilt for the TPU memory system:

- XLA's row scatter costs ~5-10 ns/row in per-row DMA descriptors, and the
  bucketed ``lax.switch`` the round-3 builder used forced buffer-unification
  copies of the whole row store every split (PERF.md).  Together those were
  ~45% of every boosting iteration.
- This kernel instead streams the parent leaf's window through VMEM in
  ``chunk``-row double-buffered tiles, routes each row (same binned-decision
  semantics as ``tree_learner._route_left``), and *places* rows with a one-hot
  permutation matmul on the MXU — left rows compact to the window's front
  (in-place, behind the read cursor), right rows stream to a scratch region
  and are copied back after the left block settles.  Every HBM touch is a
  contiguous >=64 KB DMA at a 32-row-aligned offset: zero per-row descriptors,
  no switch, cost proportional to the window.
- The smaller child's histogram (serial_tree_learner.cpp:347-356 subtraction
  trick feeds on it) accumulates in the same pass from the same VMEM tiles —
  the routing/scatter/histogram fusion PERF.md round 3 listed as the next
  lever.
- Round 6: the chunk loop is SOFTWARE-PIPELINED — phase C (scalar blends +
  flushes) trails behind phases A/B on banked totals and placement buffers,
  so the per-chunk totals VMEM->SMEM round-trip and the flush-semaphore
  waits overlap the next chunk's matmuls instead of stalling them (round 5
  measured phase A at ~10x its isolated compute replica, all scheduling);
  the per-feature-group histogram loops are ROLLED (dynamic group index) so
  program size stays O(1) in F and wide-F row stores compile.
- Round 7 (size-bucketed kernels): per-split cost now scales with the leaf
  WINDOW instead of paying one fixed CHUNK=4096 pipeline on every split —
  the documented remaining gap in the 1M-row head-to-head, where deep-tree
  leaf windows shrink below one chunk and per-split fixed cost dominates:
  (a) the totals round-trip is ONE VMEM->SMEM DMA per ``totk`` chunks (the
  double-banked layout generalized to group banks; phase C trails ``totk``
  chunks behind A/B instead of one), (b) ``chunk`` itself is a parameter —
  1024 for mid windows so they stop padding to the 4096-row floor, 4096 for
  the streaming regime — and (c) a SMALL-WINDOW kernel variant handles
  sub-chunk leaves (the majority of splits at num_leaves=255 on <=1M rows):
  single chunk, no input ring, no deferred phase C, no totals DMA at all —
  lane-resident totals drive an in-register permutation and one write-back
  DMA.  :func:`fused_bucket_plan` is the dispatch schedule the tree builder
  switches over (bucket choice by window size; the variant set is
  trace-static so the fused ``lax.scan`` boosting path compiles once).
  All variants share the same phase-A/histogram building blocks, so
  interpret-mode numerics are bit-exact across buckets (pinned by
  tests/test_partition_buckets.py).

Mosaic constraints honored (probed on v5e): no u8 vector arithmetic (u8 used
only for DMA/select; math in i32/bf16/f32), no dynamic sublane rotate on u8
(placement is done by matmul, not roll), dynamic DMA offsets must be provably
32-row aligned (``pl.multiple_of`` + by-construction alignment).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram import (_accum_factored_all, _accum_onehot_all,
                        _colf_rows_dyn, _extract_values_T,
                        _factored_out_shape, _fold_factored, _hilo_split,
                        _padded_features, _use_factored, histogram_xla_masked,
                        rows_split_xla)

_LANE = 128
_ALIGN = 32          # u8 sublane tile: dynamic DMA offsets must be 32-row mult
CHUNK = 4096         # rows per streamed DMA tile of the LARGE bucket; also
                     # the row-store padding contract (spare rows past every
                     # window) every variant relies on
SMALL_CHUNK = 1024   # the small-window kernel's single-chunk capacity
T = 128              # rows per placement subtile (one P matmul)
TS = 128             # staging/flush tile (rows per contiguous write-back)
# Round-5 (2M-row window, v5e, full-kernel timings — phase knockouts are
# scheduling-noisy, whole-kernel numbers are stable): the lane-packed
# phase A/B + factored-MXU histogram rewrite took 9.29 -> 4.6 ns/row at
# CHUNK=2048; CHUNK=4096 amortizes the per-chunk totals round-trip to
# 4.12 (8192: 3.98, but doubles the minimum per-split window work that
# small deep-tree leaves pay — round 7 removes that floor with the bucket
# schedule below instead).  T=128 halves the placement one-hot vs 256
# now that dest math is lane-major.
NIN = 3              # input-chunk ring depth: two reads in flight so the
                     # read DMA wait overlaps the previous chunk's phase
                     # A/B matmuls AND the trailing phase C (round 6)
_MID_MAX = 16384     # bucket bound: windows <= this use the 1024-row chunk

assert T == TS and T % _ALIGN == 0 and T == _LANE
assert NIN >= 2
assert CHUNK % SMALL_CHUNK == 0 and SMALL_CHUNK % T == 0


def _ring_depth(chunk: int) -> int:
    """Flush-ring depth per stream: >= chunk/TS + 2 so a whole chunk can
    blend before its flushes start (single-flush circular staging depends on
    nls <= TS per subtile — at most one stage wrap per append — and the
    subtile loop covering the chunk exactly; retuning one constant without
    the other silently corrupts the partition)."""
    return chunk // TS + 4


def _totk(chunk: int) -> int:
    """Chunks per totals VMEM->SMEM DMA window (round 7): one round-trip per
    ~8192 rows.  The group-banked layout stores ``totk`` chunks' subtile
    totals per bank; phase C trails ``totk`` chunks behind phase A/B, so the
    DMA has a full group of matmuls to land behind (2 for chunk=4096, 8 for
    chunk=1024)."""
    return max(1, 8192 // chunk)


def fused_bucket_plan(n: int) -> tuple:
    """Trace-static dispatch schedule for the fused split pass over an
    ``n``-row store: ``((small, chunk, max_wc), ..., (small, chunk, None))``,
    buckets ascending, last bucket unbounded.  The tree builder selects the
    bucket by the split window's row count (``jnp.searchsorted`` over the
    bounds), so sub-chunk leaves pay the small kernel's single-chunk cost and
    mid windows stop padding to the 4096-row floor; every variant is
    bit-exact vs the others in interpret mode (same accumulation order).

    The small bucket's bound leaves ``_ALIGN`` rows of slack: the kernel
    processes [wb_al, wb_al + SMALL_CHUNK) and the window head offset
    ``wb - wb_al`` can reach _ALIGN - 1."""
    plan = []
    small_max = SMALL_CHUNK - _ALIGN
    if small_max < n:
        plan.append((True, SMALL_CHUNK, small_max))
    if _MID_MAX < n:
        plan.append((False, SMALL_CHUNK, _MID_MAX))
        plan.append((False, CHUNK, None))
    else:
        plan.append((False, SMALL_CHUNK, None))
    return tuple(plan)


class _ScalRow:
    """One window's scalar-prefetch row: ``scal[i]`` reads ``scal_ref[i]``
    for the single-window kernels and ``scal_ref[g, i]`` for a grid step of
    the multi-window (level-batched) variants — the kernel bodies and their
    shared building blocks (:func:`_route_tile`, :func:`_hist_tile`) index
    the view identically in both modes, which is what keeps the
    level-batched launch bit-exact against a sequence of single-window
    launches (same op sequence per window)."""

    def __init__(self, ref, g=None):
        self._ref = ref
        self._g = g

    def __getitem__(self, i):
        if self._g is None:
            return self._ref[i]
        return self._ref[self._g, i]


def _route_tile(col, scal_ref, num_bins):
    """go-left decision as a [T, 1] i32 0/1 vector (Mosaic cannot truncate i8
    vectors to i1, so boolean logic stays in i32 arithmetic); scalar split
    description from SMEM (bitset words ride in scal[12:] as i32).  Same
    semantics as tree_learner._route_left (tree.h:262-331)."""
    thr = scal_ref[3]
    default_left = scal_ref[4]
    mt = scal_ref[5]
    nb = scal_ref[6]
    dbin = scal_ref[7]
    is_cat = scal_ref[8] == 1
    use_unfold = scal_ref[10] == 1
    eoff = scal_ref[11]
    # EFB group code -> feature bin (tree_learner._unfold_bin)
    in_range = ((col >= eoff).astype(jnp.int32)
                * (col <= eoff + nb - 2).astype(jnp.int32))
    unfolded = jnp.where(in_range == 1, col - eoff + 1, 0)
    col = jnp.where(use_unfold, unfolded, col)
    is_missing = jnp.where(
        mt == 1, (col == nb - 1).astype(jnp.int32),          # MissingType.NAN
        jnp.where(mt == 2, (col == dbin).astype(jnp.int32),  # MissingType.ZERO
                  jnp.zeros_like(col)))
    num_left = jnp.where(is_missing == 1,
                         jnp.full_like(col, 1) * default_left,
                         (col <= thr).astype(jnp.int32))
    # categorical: bin membership in the left bitset words
    word = jnp.zeros_like(col)
    for wd in range(num_bins // 32):
        word = jnp.where((col >> 5) == wd, scal_ref[12 + wd], word)
    cat_left = (word >> (col & 31)) & 1
    return jnp.where(is_cat, cat_left, num_left)


# ---- phase-A building blocks shared by every kernel variant (round 7) ----
# Bucketed kernels must stay BIT-EXACT against each other in interpret mode
# (the dispatch assigns each window size to exactly one bucket, but the test
# suite pins cross-variant equality so a retune can never shift numerics);
# sharing the op sequence is what guarantees it.


def _extract_col_lanes(ti_i8, gcol, *, W, bpc, packed, npk):
    """ONE i8 x i8 -> i32 MXU dot extracts the split column for a whole
    [npk*128, W] i8 tile, TRANSPOSED ([2, W] @ [R, W]^T) so the result and
    the packed reshape stay lane-major; & 255 undoes the signed-byte wrap.
    Returns the lane-packed [npk, 128] i32 bin codes."""
    lanes_w = jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)
    if packed:
        colsel = (lanes_w == gcol // 2).astype(jnp.int8)
        colsel2 = jnp.zeros((1, W), jnp.int8)
    elif bpc == 2:
        colsel = (lanes_w == 2 * gcol).astype(jnp.int8)
        colsel2 = (lanes_w == 2 * gcol + 1).astype(jnp.int8)
    else:
        colsel = (lanes_w == gcol).astype(jnp.int8)
        colsel2 = jnp.zeros((1, W), jnp.int8)
    wmat = jnp.concatenate([colsel, colsel2], axis=0)        # [2, W]
    extTi = jax.lax.dot_general(
        wmat, ti_i8, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)                    # [2, R]
    lo_p = extTi[0:1, :].reshape(npk, _LANE) & 255
    if packed:
        return jnp.where(gcol % 2 == 1, (lo_p >> 4) & 15, lo_p & 15)
    if bpc == 2:
        return lo_p | ((extTi[1:2, :].reshape(npk, _LANE) & 255) << 8)
    return lo_p


def _subtile_prefixes(S_L, S_R, ltri, *, nsub):
    """Per-subtile inclusive prefixes + per-side cumulative totals, all
    lane-resident: S stacks the selection vectors as [2*nsub, T] lane-major
    (row s = left stream of subtile s, row nsub+s = right) so the prefixes
    are ONE [2*nsub, T] @ upper-tri[T, T] MXU dot and the cross-subtile
    cumulative totals one tiny dot more.  Per-subtile totals <= T = 128, so
    the f32/bf16 hop for the tiny triB dot stays exact.
    Returns (pfxU [2*nsub, T] i32, tot_col, incl_col, excl_col [2*nsub, 1]
    f32)."""
    S = jnp.concatenate([S_L, S_R], axis=0).astype(jnp.int8)
    pfxU = jax.lax.dot_general(
        S, ltri[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                    # [2*nsub, T]
    tot_col = pfxU[:, T - 1:T].astype(jnp.float32)
    # per-side cumulative totals (lower-tri within each block)
    iiB = jax.lax.broadcasted_iota(jnp.int32, (2 * nsub, 1), 0)
    jjB = jax.lax.broadcasted_iota(jnp.int32, (1, 2 * nsub), 1)
    triB = ((iiB >= jjB).astype(jnp.int32)
            * ((iiB < nsub) == (jjB < nsub)).astype(jnp.int32)
            ).astype(jnp.bfloat16)
    incl_col = jax.lax.dot_general(
        triB, tot_col.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                  # [2*nsub, 1]
    return pfxU, tot_col, incl_col, incl_col - tot_col


def _hist_tile(ti_c, hist_ref, scal_ref, start, cnt, *, num_features,
               num_bins, bpc, packed, exact, voff, f_shard,
               quantized=False):
    """One [R, W] i32 row-store tile's histogram += contribution for the
    rows at TILE-RELATIVE positions [start, start + cnt) — the shared
    accumulation op of the streamed hist pass, the small-window kernel and
    the copy-back-free right block (``start``/``cnt`` may be scalars or
    [1, 1] lane vectors; out-of-range rows contribute exact zeros, so the
    accumulated value is independent of the tile height R up to fp-identity
    adds)."""
    rows_n = ti_c.shape[0]
    if _use_factored(num_features, num_bins, quantized):
        # rolled fori_loop over feature groups (round 6): program size is
        # O(p) in F, so wide-F row stores compile instead of unrolling
        # hundreds of groups
        ti_bf_h = ti_c.astype(jnp.bfloat16)
        posT = jax.lax.broadcasted_iota(jnp.int32, (1, rows_n), 1)
        inwT = ((posT >= start).astype(jnp.float32)
                * (posT < start + cnt).astype(jnp.float32))
        fb = (scal_ref[12 + num_bins // 32] if f_shard else 0)
        v4T = _extract_values_T(ti_bf_h, voff=voff, exact=exact, inwT=inwT,
                                quantized=quantized)
        _accum_factored_all(ti_bf_h, v4T, hist_ref,
                            num_features=num_features, num_bins=num_bins,
                            bpc=bpc, packed=packed, f_base=fb,
                            quantized=quantized)
        return
    # classic fallback (accumulators past the factored 4 MiB gate, i.e.
    # wide F): rolled fori_loop over lane tiles with dynamic-index column
    # extraction; the value path extracts via bf16 dots (it needs bf16
    # operands anyway)
    iota_lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    bwh = [(iota_lane == off).astype(jnp.bfloat16)
           + (iota_lane == off + 1).astype(jnp.bfloat16) * 256
           for off in (voff, voff + 2, voff + 4, voff + 6)]
    wmat_h = jnp.concatenate(bwh, axis=0)                    # [4, W]
    ext_h = jax.lax.dot_general(
        ti_c.astype(jnp.bfloat16), wmat_h,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                  # [R, 4]
    exti_h = ext_h.astype(jnp.int32)
    g = jax.lax.bitcast_convert_type(
        exti_h[:, 0:1] | (exti_h[:, 1:2] << 16), jnp.float32)
    h = jax.lax.bitcast_convert_type(
        exti_h[:, 2:3] | (exti_h[:, 3:4] << 16), jnp.float32)
    pos = jax.lax.broadcasted_iota(jnp.int32, (rows_n, 1), 0)
    inw = ((pos >= start).astype(jnp.float32)
           * (pos < start + cnt).astype(jnp.float32))
    vals = jnp.concatenate([g * inw, h * inw], axis=1)
    v4 = _hilo_split(vals, axis=1, exact=exact, quantized=quantized)
    colf = _colf_rows_dyn(ti_c, bpc=bpc, packed=packed)
    _accum_onehot_all(colf, v4, hist_ref, num_features=num_features,
                      num_bins=num_bins, contract_dim=0)


def _make_partition_kernel(*, n_pad, W, num_features, num_bins, voff, bpc,
                           packed, exact, f_shard=False, dbg_skip="",
                           chunk=CHUNK, multiwin=False, quantized=False):
    # f_shard: the histogrammed feature window starts at scal[12 + B//32]
    # (feature-parallel shards build only their own F/d block while routing
    # on the full row store); num_features is then the WINDOW's width
    del n_pad  # shapes come from the refs; kept for cache-key clarity
    nb_ring = _ring_depth(chunk)
    totk = _totk(chunk)
    ncb = totk + 1           # comp_buf banks: totk chunks awaiting phase C
                             # plus the chunk being placed

    def kernel(scal_ref, rows_in_ref, rows_ref, scratch_ref, hist_ref,
               stats_ref, inbuf, stage, ltri, rot, tmp, comp_buf,
               totals_vm, totals_sm,
               sem_in, sem_pre, sem_fl, sem_fr, sem_cb, sem_tot):
        # rows_in_ref is the pre-alias view of rows_ref (same buffer); all
        # reads and writes go through rows_ref so ordering is explicit.
        # stage is a [2*nb_ring, TS, W] ring: slots [0, nb_ring) buffer the
        # left stream, [nb_ring, 2*nb_ring) the right stream.  Flush DMAs
        # are ASYNC — a slot's previous flush is awaited only when the ring
        # wraps back to it (nb_ring-1 flushes of slack), so the VPU/MXU
        # never stalls on HBM writes (sync flushes were ~60% of the kernel
        # in round-4 profiles).
        del rows_in_ref
        scal = (_ScalRow(scal_ref, pl.program_id(0)) if multiwin
                else scal_ref)
        wb = scal[0]
        wc = scal[1]
        gcol = scal[2]
        hist_left = scal[9]

        wb_al = pl.multiple_of((wb // _ALIGN) * _ALIGN, _ALIGN)
        headL = wb - wb_al
        nchunks = (headL + wc + chunk - 1) // chunk

        hist_ref[...] = jnp.zeros_like(hist_ref)
        # upper-triangular ones U[j, t] = (j <= t): subtiles are STACKED
        # ALONG M so one [2*nsub, T] @ U dot computes every subtile's local
        # inclusive prefix lane-major — a skinny N=2 prefix matmul is MXU
        # weight-load bound (~2.3us each), and sublane-major prefixes would
        # put every per-row intermediate in 128x-padded [CHUNK, 1] vregs
        ltri[...] = (jax.lax.broadcasted_iota(jnp.int32, (T, 1), 0)
                     <= jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
                     ).astype(jnp.int8)

        def left_dst(nf):
            return pl.multiple_of(wb_al + nf * TS, _ALIGN)

        # prefill the left stage's head with the old rows [wb_al, wb) so the
        # first aligned flush preserves the neighbour leaf's rows
        cp = pltpu.make_async_copy(
            rows_ref.at[pl.ds(wb_al, _ALIGN)],
            stage.at[0, pl.ds(0, _ALIGN)], sem_pre)
        cp.start()
        cp.wait()

        # deepened input ring: NIN - 1 reads in flight, so the chunk-read
        # semaphore wait overlaps the previous chunk's phase A/B matmuls and
        # the trailing phase C (software pipeline below)
        for j in range(NIN - 1):
            @pl.when(j < nchunks)
            def _prologue(j=j):
                pltpu.make_async_copy(
                    rows_ref.at[pl.ds(
                        pl.multiple_of(wb_al + j * chunk, _ALIGN), chunk)],
                    inbuf.at[j], sem_in.at[j]).start()

        iota2ts1 = jax.lax.broadcasted_iota(jnp.int32, (2 * TS, 1), 0)
        iota_ts = jax.lax.broadcasted_iota(jnp.int32, (TS, 1), 0)
        totals_on = "totals" not in dbg_skip and "prefix" not in dbg_skip
        nsub = chunk // T
        npk = chunk // _LANE                   # lane-packed rows (row r ->
                                               # [r // 128, r % 128])

        def wait_left(m):
            sl = jax.lax.rem(m, nb_ring)
            pltpu.make_async_copy(
                stage.at[sl], rows_ref.at[pl.ds(left_dst(m), TS)],
                sem_fl.at[sl]).wait()

        def wait_right(m):
            sl = jax.lax.rem(m, nb_ring)
            pltpu.make_async_copy(
                stage.at[nb_ring + sl],
                scratch_ref.at[pl.ds(pl.multiple_of(m * TS, _ALIGN), TS)],
                sem_fr.at[sl]).wait()

        # ---- software pipeline (rounds 6-7) ----
        # The round-5 kernel ran A -> B -> totals-DMA-wait -> C per chunk:
        # the VMEM->SMEM totals round-trip and the flush-ring semaphore
        # waits sat on the critical path every chunk (PERF.md measured the
        # residual phase-A cost at ~10x its isolated compute replica — all
        # scheduling).  Round 6 deferred phase C one chunk; round 7 widens
        # the totals window: chunks write their subtile totals into GROUP
        # banks of ``totk`` chunks, ONE DMA per group ships the whole bank
        # to SMEM, and phase C (scalar blends + flushes) trails ``totk``
        # chunks behind phase A/B — the group's first phase C awaits a DMA
        # that has had a full group of matmuls to land.  Phase B never
        # needs the scalar fill counters — the cumulative placed-row counts
        # ride the A/B stage as lane-resident [1, 1] vectors (cumLv/cumRv),
        # bit-equal to the SMEM-derived scalars phase C still uses for DMA
        # offsets.
        def chunk_ab(c, cum):
            cumLv, cumRv = cum
            slot = jax.lax.rem(c, NIN)
            pltpu.make_async_copy(
                rows_ref.at[pl.ds(pl.multiple_of(wb_al + c * chunk, _ALIGN),
                                  chunk)],
                inbuf.at[slot], sem_in.at[slot]).wait()

            @pl.when(c + NIN - 1 < nchunks)
            def _prefetch():
                nxt = jax.lax.rem(c + NIN - 1, NIN)
                pltpu.make_async_copy(
                    rows_ref.at[pl.ds(
                        pl.multiple_of(wb_al + (c + NIN - 1) * chunk,
                                       _ALIGN), chunk)],
                    inbuf.at[nxt], sem_in.at[nxt]).start()

            abs0 = wb_al + c * chunk
            # ---- phase A (vector): convert, route, per-subtile prefixes.
            # EVERY per-row intermediate lives LANE-PACKED as [chunk/128,
            # 128] — [chunk, 1]-shaped vectors are 128x vreg-padded on v5e
            # and made this phase 2.6 ns/row in the round-5 knockout profile
            # (~90% of phase A); the same math lane-packed is ~30 vregs per
            # chunk.  Per-subtile totals land in SMEM via group DMAs (direct
            # vector->scalar extraction costs ~0.7us EACH and does not
            # pipeline).  The streamed tile is used ONLY through i8 x i8 ->
            # i32 MXU dots (probed exact on v5e), so a zero-cost bitcast
            # VIEW replaces the round-4/5 u8 -> i32 -> bf16 tile converts;
            # signed-byte wrap is undone with & 255 after each dot
            if "convert" in dbg_skip:          # profiling: stream-only floor
                ti_i8 = jnp.zeros((chunk, W), jnp.int8)
            elif "statslot" in dbg_skip:       # profiling: static buffer read
                ti_i8 = jax.lax.bitcast_convert_type(inbuf[0], jnp.int8)
            else:
                ti_i8 = jax.lax.bitcast_convert_type(inbuf[slot], jnp.int8)
            if "extract" in dbg_skip:          # profiling: no extract/route
                col_p = jnp.zeros((npk, _LANE), jnp.int32)
            else:
                col_p = _extract_col_lanes(ti_i8, gcol, W=W, bpc=bpc,
                                           packed=packed, npk=npk)
            gl_p = _route_tile(col_p, scal, num_bins)        # [npk, 128]
            pos_p = (abs0
                     + jax.lax.broadcasted_iota(jnp.int32, (npk, 1), 0)
                     * _LANE
                     + jax.lax.broadcasted_iota(jnp.int32, (1, _LANE), 1))
            inw_p = ((pos_p >= wb).astype(jnp.int32)
                     * (pos_p < wb + wc).astype(jnp.int32))
            selL_p = gl_p * inw_p                            # i32 0/1
            selR_p = (1 - gl_p) * inw_p
            assert T % _LANE == 0
            if T == _LANE:
                S_L, S_R = selL_p, selR_p
            else:
                S_L = selL_p.reshape(nsub, T)
                S_R = selR_p.reshape(nsub, T)
            # round-7 group banking: chunk c's totals live at bank row
            # gpar*totk + kk, reused by chunk c + 2*totk — whose phase A
            # runs only after this group's DMA was awaited by phase
            # C(c - totk) (C trails totk chunks, so the reuse never races
            # the in-flight copy)
            kk = jax.lax.rem(c, totk)
            gpar = jax.lax.rem(c // totk, 2)
            bankt = gpar * totk + kk
            bankb = jax.lax.rem(c, ncb)
            if "prefix" in dbg_skip:           # profiling: no prefix/totals
                pfxU = jnp.zeros((2 * nsub, T), jnp.int32)
                excl_col = jnp.zeros((2 * nsub, 1), jnp.float32)
                incl_col = jnp.zeros((2 * nsub, 1), jnp.float32)
            else:
                pfxU, tot_col, incl_col, excl_col = _subtile_prefixes(
                    S_L, S_R, ltri, nsub=nsub)
                if totals_on:
                    totals_vm[bankt, 0:2 * nsub, 0:1] = tot_col.astype(
                        jnp.int32)
                    totals_vm[bankt, 0:2 * nsub, 1:2] = incl_col.astype(
                        jnp.int32)

                    @pl.when((kk == totk - 1) | (c == nchunks - 1))
                    def _start_totals():
                        # ONE DMA ships the whole group's totals (partial
                        # final groups ship stale tail rows phase C never
                        # reads); awaited by phase C of the group's FIRST
                        # chunk, a full ``totk`` chunks of matmuls later,
                        # so the round-trip is off the critical path
                        base = pl.multiple_of(gpar * totk, totk)
                        pltpu.make_async_copy(
                            totals_vm.at[pl.ds(base, totk)],
                            totals_sm.at[pl.ds(base, totk)],
                            sem_tot.at[gpar]).start()

            # ---- phase B (vector, back-to-back with phase A — the totals
            # DMA and the trailing phase C overlap it): place every
            # subtile into this chunk's comp_buf bank.  The placement
            # one-hot is built TRANSPOSED — dest as a [1, T] lane vector
            # against a [2TS, 1] iota — so the dest math is lane-packed
            # too; the [2TS, T] @ [T, W] dot then lands rows directly in
            # staging order.  The cross-chunk fill counters enter as the
            # lane-resident cumLv/cumRv (phase B no longer reads SMEM).
            for s in range(nsub) if "phaseB" not in dbg_skip else []:
                selLs = S_L[s:s + 1, :]                      # [1, T] i32
                selRs = S_R[s:s + 1, :]
                pfxLs = pfxU[s:s + 1, :]                     # [1, T] i32
                pfxRs = pfxU[nsub + s:nsub + s + 1, :]
                bL = excl_col[s:s + 1, 0:1].astype(jnp.int32)
                bR = excl_col[nsub + s:nsub + s + 1, 0:1].astype(jnp.int32)
                destL = jax.lax.rem(headL + cumLv + bL + pfxLs - 1, TS)
                destR = TS + jax.lax.rem(cumRv + bR + pfxRs - 1, TS)
                dest = jnp.where(selLs == 1, destL,
                                 jnp.where(selRs == 1, destR, 2 * TS))
                Pt = (dest == iota2ts1).astype(jnp.int8)         # [2TS, T]
                comp_i = jax.lax.dot_general(
                    Pt, ti_i8[s * T:(s + 1) * T, :],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)            # [2TS, W]
                comp_buf[bankb, s * 2 * TS:(s + 1) * 2 * TS, :] = (
                    comp_i & 255).astype(jnp.uint8)

            # per-side chunk totals ride the carry as [1, 1] vectors (exact:
            # counts <= chunk << 2^24, and the bf16 operands of the incl dot
            # are exact 0/1 and <= 128 values)
            totL = incl_col[nsub - 1:nsub, 0:1].astype(jnp.int32)
            totR = incl_col[2 * nsub - 1:2 * nsub, 0:1].astype(jnp.int32)
            return cumLv + totL, cumRv + totR

        def chunk_c(c, cc):
            # phase C for chunk c (scalar blends + flushes), running ``totk``
            # CHUNKS behind phase A/B: the group's banked totals DMA has had
            # a full group of matmuls to land, so the once-per-group wait
            # below is free in steady state.
            fillL, fillR, nfL, nfR, wdL, wdR = cc
            kk = jax.lax.rem(c, totk)
            gpar = jax.lax.rem(c // totk, 2)
            bankt = gpar * totk + kk
            bankb = jax.lax.rem(c, ncb)
            if totals_on:
                @pl.when(kk == 0)
                def _await_totals():
                    base = pl.multiple_of(gpar * totk, totk)
                    pltpu.make_async_copy(
                        totals_vm.at[pl.ds(base, totk)],
                        totals_sm.at[pl.ds(base, totk)],
                        sem_tot.at[gpar]).wait()
                accL = fillL + totals_sm[bankt, nsub - 1, 1]
                accR = fillR + totals_sm[bankt, 2 * nsub - 1, 1]
            else:                              # "prefix"/"totals" knockouts
                accL, accR = fillL, fillR
            k1L = (headL + accL) // TS       # stream tiles complete after c
            k1R = accR // TS

            # await ring slots this chunk will reuse (flushes older than the
            # ring depth)
            if "flush" not in dbg_skip:
                wdL = jax.lax.fori_loop(
                    wdL, jnp.maximum(wdL, k1L - nb_ring + 1),
                    lambda m, w: (wait_left(m), w + 1)[1], wdL)
                wdR = jax.lax.fori_loop(
                    wdR, jnp.maximum(wdR, k1R - nb_ring + 1),
                    lambda m, w: (wait_right(m), w + 1)[1], wdR)

            for s in range(nsub) if "phaseC" not in dbg_skip else []:
                compL = comp_buf[bankb, s * 2 * TS:s * 2 * TS + TS, :]
                compR = comp_buf[bankb, s * 2 * TS + TS:(s + 1) * 2 * TS, :]
                nls = totals_sm[bankt, s, 0]
                nrs = totals_sm[bankt, nsub + s, 0]
                baseL = fillL + totals_sm[bankt, s, 1] - nls
                baseR = fillR + totals_sm[bankt, nsub + s, 1] - nrs
                startL = jax.lax.rem(headL + baseL, TS)
                startR = jax.lax.rem(baseR, TS)
                curL = jax.lax.rem((headL + baseL) // TS, nb_ring)
                nxtL = jax.lax.rem((headL + baseL) // TS + 1, nb_ring)
                curR = nb_ring + jax.lax.rem(baseR // TS, nb_ring)
                nxtR = nb_ring + jax.lax.rem(baseR // TS + 1, nb_ring)

                # blend the unwrapped circular ranges (masks in i32: Mosaic
                # cannot truncate i8 bool vectors to i1)
                maskLu = ((iota_ts >= startL).astype(jnp.int32)
                          * (iota_ts < startL + nls).astype(jnp.int32))
                stage[curL, :, :] = jnp.where(maskLu == 1, compL,
                                              stage[curL, :, :])
                maskRu = ((iota_ts >= startR).astype(jnp.int32)
                          * (iota_ts < startR + nrs).astype(jnp.int32))
                stage[curR, :, :] = jnp.where(maskRu == 1, compR,
                                              stage[curR, :, :])

                @pl.when(startL + nls > TS)
                def _wrap_left():
                    maskLw = (iota_ts < startL + nls - TS).astype(jnp.int32)
                    stage[nxtL, :, :] = jnp.where(maskLw == 1, compL,
                                                  stage[nxtL, :, :])

                @pl.when(startR + nrs > TS)
                def _wrap_right():
                    maskRw = (iota_ts < startR + nrs - TS).astype(jnp.int32)
                    stage[nxtR, :, :] = jnp.where(maskRw == 1, compR,
                                                  stage[nxtR, :, :])

            # start this chunk's completed-tile flushes (scalar-only loops)
            def start_left(m, _):
                sl = jax.lax.rem(m, nb_ring)
                pltpu.make_async_copy(
                    stage.at[sl], rows_ref.at[pl.ds(left_dst(m), TS)],
                    sem_fl.at[sl]).start()
                return 0

            def start_right(m, _):
                sl = jax.lax.rem(m, nb_ring)
                pltpu.make_async_copy(
                    stage.at[nb_ring + sl],
                    scratch_ref.at[pl.ds(pl.multiple_of(m * TS, _ALIGN), TS)],
                    sem_fr.at[sl]).start()
                return 0

            if "flush" not in dbg_skip:
                jax.lax.fori_loop(nfL, k1L, start_left, 0)
                jax.lax.fori_loop(nfR, k1R, start_right, 0)

            return accL, accR, k1L, k1R, wdL, wdR

        zero = jnp.int32(0)
        zv = jnp.zeros((1, 1), jnp.int32)

        def pipe_body(c, carry):
            # steady state: A/B of chunk c overlaps the in-flight totals DMA
            # of the previous group, whose phase C trails ``totk`` chunks
            # behind (the inner fori_loop has exactly one trip for
            # c >= totk and zero before)
            cumLv, cumRv, fillL, fillR, nfL, nfR, wdL, wdR = carry
            cumLv, cumRv = chunk_ab(c, (cumLv, cumRv))
            cc = jax.lax.fori_loop(jnp.maximum(c - totk, 0),
                                   jnp.maximum(c - totk + 1, 0), chunk_c,
                                   (fillL, fillR, nfL, nfR, wdL, wdR))
            return (cumLv, cumRv) + cc

        carry = jax.lax.fori_loop(
            0, nchunks, pipe_body,
            (zv, zv, zero, zero, zero, zero, zero, zero))
        # pipeline epilogue: the trailing ``totk`` chunks' phase C
        fillL, fillR, nfL, nfR, wdL, wdR = jax.lax.fori_loop(
            jnp.maximum(nchunks - totk, 0), nchunks, chunk_c, carry[2:])
        nl = fillL
        nr = fillR
        stats_ref[0, 0] = nl

        # drain the outstanding async flushes
        if "flush" not in dbg_skip:
            jax.lax.fori_loop(wdL, nfL,
                              lambda m, w: (wait_left(m), w + 1)[1], wdL)
            jax.lax.fori_loop(wdR, nfR,
                              lambda m, w: (wait_right(m), w + 1)[1], wdR)

        # ---- final right partial flush (scratch is all ours: no RMW,
        # garbage tail rows are masked by nr during copy-back) ----
        pend_r = fillR - nfR * TS

        @pl.when(pend_r > 0)
        def _final_right():
            cpf = pltpu.make_async_copy(
                stage.at[nb_ring + jax.lax.rem(nfR, nb_ring)],
                scratch_ref.at[pl.ds(pl.multiple_of(nfR * TS, _ALIGN), TS)],
                sem_pre)
            cpf.start()
            cpf.wait()

        # ---- final left partial flush (read-modify-write) ----
        pend_l = headL + fillL - nfL * TS

        @pl.when(pend_l > 0)
        def _final_left():
            src = left_dst(nfL)
            cpa = pltpu.make_async_copy(rows_ref.at[pl.ds(src, TS)],
                                        tmp.at[0], sem_pre)
            cpa.start()
            cpa.wait()
            keep = iota_ts < pend_l
            tmp[0, :, :] = jnp.where(keep,
                                     stage[jax.lax.rem(nfL, nb_ring), :, :],
                                     tmp[0, :, :])
            cpb = pltpu.make_async_copy(tmp.at[0], rows_ref.at[pl.ds(src, TS)],
                                        sem_pre)
            cpb.start()
            cpb.wait()

        # ---- smaller child's histogram from its CONTIGUOUS block ----
        # Post-partition the smaller child is contiguous (left block in
        # rows_ref, right block in scratch).  With the factored hi/lo build
        # (histogram._accum_factored_group) the per-row cost is nhi + nlo
        # compares per feature instead of B — near-independent of max_bin —
        # and the outer product rides the MXU contraction; wide-F datasets
        # fall back to the classic packed one-hot tiles.
        if "hist" not in dbg_skip:
            def hist_pass(src_ref, base_al, head, cnt):
                nh = (head + cnt + chunk - 1) // chunk

                for j in range(NIN - 1):
                    @pl.when(j < nh)
                    def _pro(j=j):
                        pltpu.make_async_copy(
                            src_ref.at[pl.ds(
                                pl.multiple_of(base_al + j * chunk, _ALIGN),
                                chunk)],
                            inbuf.at[j], sem_in.at[j]).start()

                def hbody(c, _):
                    slot = jax.lax.rem(c, NIN)
                    pltpu.make_async_copy(
                        src_ref.at[pl.ds(
                            pl.multiple_of(base_al + c * chunk, _ALIGN),
                            chunk)],
                        inbuf.at[slot], sem_in.at[slot]).wait()

                    @pl.when(c + NIN - 1 < nh)
                    def _pre():
                        nxt = jax.lax.rem(c + NIN - 1, NIN)
                        pltpu.make_async_copy(
                            src_ref.at[pl.ds(
                                pl.multiple_of(base_al + (c + NIN - 1)
                                               * chunk, _ALIGN), chunk)],
                            inbuf.at[nxt], sem_in.at[nxt]).start()

                    ti_c = inbuf[slot].astype(jnp.int32)
                    _hist_tile(ti_c, hist_ref, scal,
                               head - c * chunk, cnt,
                               num_features=num_features, num_bins=num_bins,
                               bpc=bpc, packed=packed, exact=exact,
                               voff=voff, f_shard=f_shard,
                               quantized=quantized)
                    return 0

                jax.lax.fori_loop(0, nh, hbody, 0)

            @pl.when(hist_left == 1)
            def _hist_left_block():
                hist_pass(rows_ref, wb_al, headL, nl)

            @pl.when(hist_left != 1)
            def _hist_right_block():
                hist_pass(scratch_ref, 0, 0, nr)

        # ---- copy right block back: scratch[0:nr] -> rows[wb+nl ...) ----
        # Same streamed-append machinery (double-buffered reads, nb_ring-deep
        # async flush ring on the left slots), with a constant row rotation
        # by the destination's 32-row phase.
        @pl.when(nr > 0)
        def _copy_back():
            d0 = wb + nl
            d_al = pl.multiple_of((d0 // _ALIGN) * _ALIGN, _ALIGN)
            ph = d0 - d_al
            # constant row-rotation one-hot: source row j -> stage (j+ph)%TS
            rot[...] = (jax.lax.rem(
                jax.lax.broadcasted_iota(jnp.int32, (TS, 1), 0) + ph, TS)
                == jax.lax.broadcasted_iota(jnp.int32, (1, TS), 1)
            ).astype(jnp.int8)
            # head prefill: keep rows [d_al, d0) (tail of the left block)
            cph = pltpu.make_async_copy(
                rows_ref.at[pl.ds(d_al, _ALIGN)],
                stage.at[0, pl.ds(0, _ALIGN)], sem_pre)
            cph.start()
            cph.wait()
            ncbk = (nr + TS - 1) // TS

            pltpu.make_async_copy(
                scratch_ref.at[pl.ds(0, TS)], tmp.at[0], sem_in.at[0]).start()

            def cb_body(k, carry):
                fill, nf = carry
                slot = jax.lax.rem(k, 2)
                pltpu.make_async_copy(
                    scratch_ref.at[pl.ds(pl.multiple_of(k * TS, _ALIGN), TS)],
                    tmp.at[slot], sem_in.at[slot]).wait()

                @pl.when(k + 1 < ncbk)
                def _prefetch_cb():
                    nxt_in = 1 - slot
                    pltpu.make_async_copy(
                        scratch_ref.at[pl.ds(
                            pl.multiple_of((k + 1) * TS, _ALIGN), TS)],
                        tmp.at[nxt_in], sem_in.at[nxt_in]).start()

                tr = jax.lax.dot_general(
                    rot[...],
                    jax.lax.bitcast_convert_type(tmp[slot, :, :], jnp.int8),
                    (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                comp = (tr & 255).astype(jnp.uint8)              # [TS, W]
                nvs = jnp.minimum(nr - k * TS, TS)
                # valid source rows j < nvs sit at p=(ph+j)%TS
                pj = jax.lax.rem(iota_ts - ph + TS, TS)          # j of pos p
                cur = jax.lax.rem(nf, nb_ring)
                nxt = jax.lax.rem(nf + 1, nb_ring)
                mask_u = ((iota_ts >= ph).astype(jnp.int32)
                          * (pj < nvs).astype(jnp.int32))
                stage[cur, :, :] = jnp.where(mask_u == 1, comp,
                                             stage[cur, :, :])
                cross = ph + nvs >= TS

                @pl.when(cross)
                def _flush_cb():
                    @pl.when(nf >= nb_ring - 1)
                    def _await_prev():
                        pltpu.make_async_copy(
                            stage.at[nxt],
                            rows_ref.at[pl.ds(pl.multiple_of(
                                d_al + (nf - (nb_ring - 1)) * TS, _ALIGN),
                                TS)],
                            sem_cb.at[nxt]).wait()
                    pltpu.make_async_copy(
                        stage.at[cur],
                        rows_ref.at[pl.ds(
                            pl.multiple_of(d_al + nf * TS, _ALIGN), TS)],
                        sem_cb.at[cur]).start()
                    mask_w = ((iota_ts < ph).astype(jnp.int32)
                              * (pj < nvs).astype(jnp.int32))
                    stage[nxt, :, :] = jnp.where(mask_w == 1, comp,
                                                 stage[nxt, :, :])

                return fill + nvs, nf + jnp.where(cross, 1, 0)

            fill, nf = jax.lax.fori_loop(0, ncbk, cb_body, (zero, zero))
            for j in range(1, nb_ring):
                @pl.when(nf - j >= 0)
                def _drain_cb(j=j):
                    idx = nf - j
                    sl = jax.lax.rem(idx, nb_ring)
                    pltpu.make_async_copy(
                        stage.at[sl],
                        rows_ref.at[pl.ds(pl.multiple_of(
                            d_al + idx * TS, _ALIGN), TS)],
                        sem_cb.at[sl]).wait()
            pend = ph + fill - nf * TS

            @pl.when(pend > 0)
            def _final_cb():
                src = pl.multiple_of(d_al + nf * TS, _ALIGN)
                cpa = pltpu.make_async_copy(rows_ref.at[pl.ds(src, TS)],
                                            tmp.at[0], sem_pre)
                cpa.start()
                cpa.wait()
                keep = iota_ts < pend
                tmp[0, :, :] = jnp.where(keep,
                                         stage[jax.lax.rem(nf, nb_ring), :, :],
                                         tmp[0, :, :])
                cpb = pltpu.make_async_copy(tmp.at[0],
                                            rows_ref.at[pl.ds(src, TS)],
                                            sem_pre)
                cpb.start()
                cpb.wait()

    return kernel


def _make_small_partition_kernel(*, n_pad, W, num_features, num_bins, voff,
                                 bpc, packed, exact, f_shard=False,
                                 dbg_skip="", sc=SMALL_CHUNK, multiwin=False,
                                 quantized=False):
    """Round-7 small-window variant: the whole window fits ONE ``sc``-row
    chunk (dispatch bound: wc <= sc - _ALIGN), so the entire streaming
    apparatus disappears — no input ring, no flush rings, no deferred phase
    C, no scratch output, and crucially NO totals VMEM->SMEM round-trip: the
    per-subtile prefixes stay lane-resident and drive an in-register
    permutation ([sc, T] one-hot dots accumulated into one [sc, W] tile),
    the smaller child's histogram masks the same tile, and a single DMA
    writes the window back.  Two DMAs + ~3*nsub matmuls total per split —
    the fixed cost a sub-chunk deep-tree leaf actually pays.

    Phase A (extract/route/prefix) and the histogram accumulation reuse the
    pipelined kernel's building blocks verbatim, so results are bit-exact
    against the full kernel on the same window (pinned by
    tests/test_partition_buckets.py)."""
    del n_pad
    assert dbg_skip in ("", "hist"), \
        "the small-window kernel only supports the 'hist' knockout"
    nsub = sc // T
    npk = sc // _LANE

    def kernel(scal_ref, rows_in_ref, rows_ref, hist_ref, nl_ref,
               inbuf, outbuf, ltri, sem):
        del rows_in_ref
        scal = (_ScalRow(scal_ref, pl.program_id(0)) if multiwin
                else scal_ref)
        wb = scal[0]
        wc = scal[1]
        gcol = scal[2]
        hist_left = scal[9]
        wb_al = pl.multiple_of((wb // _ALIGN) * _ALIGN, _ALIGN)
        headL = wb - wb_al

        hist_ref[...] = jnp.zeros_like(hist_ref)
        nl_ref[...] = jnp.zeros_like(nl_ref)

        # empty windows (dead leaf-wise iterations, level-batched slots
        # whose window belongs to another bucket class) skip the read,
        # permutation and write-back entirely: the partition of an empty
        # window is the identity and its histogram is the zeros above, so
        # skipping is bit-exact AND makes the per-slot cost of a
        # class-mismatched window just the grid-step bookkeeping — which is
        # what lets a level launch carry every frontier slot in every class
        @pl.when(wc > 0)
        def _run_window():
            ltri[...] = (jax.lax.broadcasted_iota(jnp.int32, (T, 1), 0)
                         <= jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
                         ).astype(jnp.int8)

            # one read covers the whole window (+ head slack); rows past the
            # window are carried through the identity permutation and written
            # back byte-identical, so the RMW is safe for the neighbour leaf
            cp = pltpu.make_async_copy(rows_ref.at[pl.ds(wb_al, sc)],
                                       inbuf, sem)
            cp.start()
            cp.wait()
            ti_i8 = jax.lax.bitcast_convert_type(inbuf[...], jnp.int8)

            # ---- phase A: shared extract/route/prefix, lane-resident ----
            col_p = _extract_col_lanes(ti_i8, gcol, W=W, bpc=bpc,
                                       packed=packed, npk=npk)
            gl_p = _route_tile(col_p, scal, num_bins)        # [npk, 128]
            pos_p = (wb_al
                     + jax.lax.broadcasted_iota(jnp.int32, (npk, 1), 0)
                     * _LANE
                     + jax.lax.broadcasted_iota(jnp.int32, (1, _LANE), 1))
            inw_p = ((pos_p >= wb).astype(jnp.int32)
                     * (pos_p < wb + wc).astype(jnp.int32))
            selL_p = gl_p * inw_p
            selR_p = (1 - gl_p) * inw_p
            if T == _LANE:
                S_L, S_R = selL_p, selR_p
            else:
                S_L = selL_p.reshape(nsub, T)
                S_R = selR_p.reshape(nsub, T)
            pfxU, _tot, incl_col, excl_col = _subtile_prefixes(S_L, S_R,
                                                               ltri,
                                                               nsub=nsub)
            nlv = incl_col[nsub - 1:nsub, 0:1].astype(jnp.int32)  # [1, 1]

            # ---- placement: window-global destinations, no staging ring --
            # dest is a permutation of [0, sc): left rows compact to
            # [headL, headL + nl), right rows to [headL + nl, headL + wc),
            # out-of-window rows keep their own position — one [sc, T]
            # one-hot dot per subtile accumulates the permuted tile (each
            # output row receives exactly one contribution)
            iota_sc = jax.lax.broadcasted_iota(jnp.int32, (sc, 1), 0)
            iota_lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
            comp_i = jnp.zeros((sc, W), jnp.int32)
            for s in range(nsub):
                selLs = S_L[s:s + 1, :]
                selRs = S_R[s:s + 1, :]
                pfxLs = pfxU[s:s + 1, :]
                pfxRs = pfxU[nsub + s:nsub + s + 1, :]
                bL = excl_col[s:s + 1, 0:1].astype(jnp.int32)
                bR = excl_col[nsub + s:nsub + s + 1, 0:1].astype(jnp.int32)
                destL = headL + bL + pfxLs - 1
                destR = headL + nlv + bR + pfxRs - 1
                own = s * T + iota_lane
                dest = jnp.where(selLs == 1, destL,
                                 jnp.where(selRs == 1, destR, own))
                Pt = (dest == iota_sc).astype(jnp.int8)          # [sc, T]
                comp_i = comp_i + jax.lax.dot_general(
                    Pt, ti_i8[s * T:(s + 1) * T, :],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)            # [sc, W]
            outbuf[...] = (comp_i & 255).astype(jnp.uint8)

            # left count out via a plain VMEM [1, 1] write — no SMEM totals
            # DMA and no vector->scalar extraction anywhere in this variant
            nl_ref[...] = nlv

            # ---- smaller child's histogram from the SAME resident tile --
            if "hist" not in dbg_skip:
                ti_c = outbuf[...].astype(jnp.int32)
                start = jnp.where(hist_left == 1,
                                  jnp.full((1, 1), 1, jnp.int32) * headL,
                                  headL + nlv)
                cnt = jnp.where(hist_left == 1, nlv, wc - nlv)
                _hist_tile(ti_c, hist_ref, scal, start, cnt,
                           num_features=num_features, num_bins=num_bins,
                           bpc=bpc, packed=packed, exact=exact, voff=voff,
                           f_shard=f_shard, quantized=quantized)

            # ---- single write-back DMA ----
            cpo = pltpu.make_async_copy(outbuf,
                                        rows_ref.at[pl.ds(wb_al, sc)],
                                        sem)
            cpo.start()
            cpo.wait()

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "num_features", "num_bins", "voff", "bpc", "packed", "exact", "interpret",
    "dbg_skip", "chunk", "small", "quantized"))
def partition_hist_pallas(rows: jax.Array, scal: jax.Array,
                          *, num_features: int,
                          num_bins: int, voff: int, bpc: int = 1,
                          packed: bool = False, exact: bool = False,
                          interpret: bool = False, dbg_skip: str = "",
                          chunk: int = CHUNK, small: bool = False,
                          quantized: bool = False):
    """Fused split pass over a combined row store.

    ``dbg_skip``: comma-joined phase knockouts for device profiling only
    ("hist", "phaseB", "phaseC", "flush", "convert", "extract", "prefix",
    "totals", "statslot") — outputs are WRONG when set ("prefix"/"totals"
    additionally zero the chunk fill counters, so even row counts lie).
    Knockout timings are scheduling-sensitive (zeroed inputs constant-fold
    downstream phases); trust whole-kernel A/B timings over deltas.

    ``chunk``/``small`` (round 7): size-bucketed kernel variants.  ``chunk``
    sets the streamed tile height of the pipelined kernel (1024 or 4096 —
    must divide the module CHUNK padding contract); ``small=True`` selects
    the single-chunk small-window kernel, valid ONLY for windows with
    ``wc <= chunk - _ALIGN`` (the dispatch schedule from
    :func:`fused_bucket_plan` guarantees it; direct callers must too).
    Every variant is bit-exact against the others in interpret mode.

    rows: [N_pad, W] u8 row store, N_pad a multiple of CHUNK.  CONTRACT: the
      caller must keep every window end <= N_pad - CHUNK (the streaming loop
      reads and the copy-back RMW writes up to a CHUNK past the window end);
      the tree builder guarantees it by always padding a full spare CHUNK.
    scal: i32 [12 + num_bins//32] (+1 optional): (window_begin,
      window_count, group_col, threshold_bin, default_left, missing_type,
      num_bin_f, default_bin, is_cat, hist_left_side, use_unfold,
      efb_offset, *cat_bitset_words[, hist_feature_begin]).  The optional
      trailing element selects a feature WINDOW for the histogram
      ([f_begin, f_begin + num_features)) — feature-parallel shards build
      only their own block (feature_parallel_tree_learner.cpp:33-52);
      routing always uses the full store.  Requires the factored path.

    Returns (rows_new [N_pad, W] u8 — the window stably partitioned in place,
    hist_raw f32 — smaller child's histogram in the kernel's accumulator
    layout (factored [G*128, p*nlo] or classic [4, f_pad*num_bins]; fold
    with :func:`fold_hist`), nl [1, 1] i32 — left-child row count).
    """
    return _partition_call(rows, scal, num_features=num_features,
                           num_bins=num_bins, voff=voff, bpc=bpc,
                           packed=packed, exact=exact, interpret=interpret,
                           dbg_skip=dbg_skip, chunk=chunk, small=small,
                           quantized=quantized)


def _partition_call(rows, scal, *, num_features, num_bins, voff, bpc,
                    packed, exact, interpret, dbg_skip, chunk, small,
                    quantized=False):
    """Shared pallas_call plumbing for the single-window
    (:func:`partition_hist_pallas`, ``scal`` 1-D) and multi-window
    (:func:`partition_hist_level_pallas`, ``scal`` [G, S]) launches: the
    window count is the grid, the per-window scalar row is selected by
    ``pl.program_id`` inside the kernel, and the hist/nl outputs are blocked
    per grid step.  A single window is exactly the G=1 blocking, so both
    entry points run the same kernels — which is what makes a level launch
    bit-exact against a sequence of per-split launches."""
    n_pad, W = rows.shape
    multiwin = scal.ndim == 2
    nwin = scal.shape[0] if multiwin else 1
    scal_width = scal.shape[-1]
    assert n_pad % CHUNK == 0, "pad the row store to a multiple of CHUNK"
    assert CHUNK % chunk == 0 and chunk % T == 0, \
        "bucketed chunk must divide the CHUNK padding contract"
    assert num_bins >= 32 and num_bins % 32 == 0, \
        "num_bins must be the >=32 kernel-block width (_pad_bins_pow2); " \
        "nibble-packed 16-bin data still scans at 32 lanes"
    f_shard = scal_width == 13 + num_bins // 32
    assert not (exact and quantized), \
        "hist_precision=quantized is incompatible with LIGHTGBM_TPU_EXACT_HIST"
    if _use_factored(num_features, num_bins, quantized):
        hist_shape = _factored_out_shape(num_features, num_bins, quantized)
    else:
        assert not f_shard, \
            "the histogram feature window needs the factored path"
        hist_shape = (2 if quantized else 4,
                      _padded_features(num_features, num_bins) * num_bins)
    h0, h1 = hist_shape

    if small:
        kernel = _make_small_partition_kernel(
            n_pad=n_pad, W=W, num_features=num_features, num_bins=num_bins,
            voff=voff, bpc=bpc, packed=packed, exact=exact, f_shard=f_shard,
            dbg_skip=dbg_skip, sc=chunk, multiwin=multiwin,
            quantized=quantized)
        rows_new, hist, nl = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(nwin,),
                in_specs=[
                    pl.BlockSpec(memory_space=pl.ANY),       # rows
                ],
                out_specs=[
                    pl.BlockSpec(memory_space=pl.ANY),       # rows (aliased)
                    pl.BlockSpec((h0, h1), lambda g, s: (g, 0)),  # hist
                    pl.BlockSpec((1, 1), lambda g, s: (g, 0)),    # nl
                ],
                scratch_shapes=[
                    pltpu.VMEM((chunk, W), jnp.uint8),       # window tile in
                    pltpu.VMEM((chunk, W), jnp.uint8),       # permuted tile
                    pltpu.VMEM((T, T), jnp.int8),            # upper-tri ones
                    pltpu.SemaphoreType.DMA,                 # read/write-back
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((n_pad, W), jnp.uint8),
                jax.ShapeDtypeStruct((nwin * h0, h1), jnp.float32),
                jax.ShapeDtypeStruct((nwin, 1), jnp.int32),
            ],
            input_output_aliases={1: 0},
            interpret=interpret,
        )(scal, rows)
        if multiwin:
            hist = hist.reshape(nwin, h0, h1)
        return rows_new, hist, nl

    nb_ring = _ring_depth(chunk)
    totk = _totk(chunk)
    nsub = chunk // T
    kernel = _make_partition_kernel(
        n_pad=n_pad, W=W, num_features=num_features, num_bins=num_bins,
        voff=voff, bpc=bpc, packed=packed, exact=exact, f_shard=f_shard,
        dbg_skip=dbg_skip, chunk=chunk, multiwin=multiwin,
        quantized=quantized)
    rows_new, _scratch, hist, nl = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nwin,),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),       # rows
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),       # rows out (aliased)
                pl.BlockSpec(memory_space=pl.ANY),       # right-block scratch
                pl.BlockSpec((h0, h1), lambda g, s: (g, 0)),  # hist
                pl.BlockSpec((1, 1), lambda g, s: (g, 0),
                             memory_space=pltpu.SMEM),        # nl
            ],
            scratch_shapes=[
                pltpu.VMEM((NIN, chunk, W), jnp.uint8),  # streamed chunk ring
                pltpu.VMEM((2 * nb_ring, TS, W), jnp.uint8),  # L/R flush rings
                pltpu.VMEM((T, T), jnp.int8),            # upper-tri prefix ones
                pltpu.VMEM((TS, TS), jnp.int8),          # copy-back rotation
                pltpu.VMEM((2, TS, W), jnp.uint8),       # RMW/cb-read bounce
                pltpu.VMEM((totk + 1, 2 * TS * nsub, W),
                           jnp.uint8),                   # placed, group banks
                pltpu.VMEM((2 * totk, 2 * nsub, 2), jnp.int32),  # totals banks
                pltpu.SMEM((2 * totk, 2 * nsub, 2), jnp.int32),  # totals land
                pltpu.SemaphoreType.DMA((NIN,)),         # chunk/cb reads
                pltpu.SemaphoreType.DMA,                 # prefills + finals
                pltpu.SemaphoreType.DMA((nb_ring,)),     # left flush ring
                pltpu.SemaphoreType.DMA((nb_ring,)),     # right flush ring
                pltpu.SemaphoreType.DMA((nb_ring,)),     # copy-back ring
                pltpu.SemaphoreType.DMA((2,)),           # totals group banks
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, W), jnp.uint8),
            jax.ShapeDtypeStruct((n_pad, W), jnp.uint8),
            jax.ShapeDtypeStruct((nwin * h0, h1), jnp.float32),
            jax.ShapeDtypeStruct((nwin, 1), jnp.int32),
        ],
        input_output_aliases={1: 0},
        interpret=interpret,
    )(scal, rows)
    if multiwin:
        hist = hist.reshape(nwin, h0, h1)
    return rows_new, hist, nl


def level_plan(n: int) -> tuple:
    """Bucket-class schedule for LEVEL-batched dispatch (round 12): the same
    size-bucket ladder as :func:`fused_bucket_plan`, reused as the per-level
    class set.  A level's frontier windows are binned into these classes by
    row count and each class gets at most ONE multi-window launch per level
    (every frontier slot rides every class launch; out-of-class slots carry
    ``wc = 0`` and are skipped in-kernel), so a tree costs at most
    ``levels * len(level_plan(n))`` launches instead of one per split."""
    return fused_bucket_plan(n)


@functools.partial(jax.jit, static_argnames=(
    "num_features", "num_bins", "voff", "bpc", "packed", "exact", "interpret",
    "chunk", "small", "quantized"))
def partition_hist_level_pallas(rows: jax.Array, scals: jax.Array,
                                *, num_features: int, num_bins: int,
                                voff: int, bpc: int = 1,
                                packed: bool = False, exact: bool = False,
                                interpret: bool = False,
                                chunk: int = CHUNK, small: bool = False,
                                quantized: bool = False):
    """Multi-window fused split pass: ONE Pallas launch partitions + child-
    histograms every window of ``scals`` ([G, S] — one
    :func:`partition_hist_pallas` scalar row per window, same layout).

    Windows must be pairwise disjoint (distinct leaves of one tree level
    are, by construction); each is processed by its own grid step of the
    SAME kernel the single-window entry point runs, so outputs are bit-exact
    against G sequential single-window launches (pinned by
    tests/test_partition_buckets.py).  Windows with ``wc = 0`` are skipped
    in-kernel (identity partition, zero histogram) — the level dispatcher
    masks out-of-class windows to 0 instead of compacting, keeping the grid
    size trace-static.

    Returns (rows_new [N_pad, W] u8, hist_raw [G, ...] f32 — per-window
    smaller-child histograms in the kernel accumulator layout (fold each
    with :func:`fold_hist`), nl [G, 1] i32 left-child counts)."""
    return _partition_call(rows, scals, num_features=num_features,
                           num_bins=num_bins, voff=voff, bpc=bpc,
                           packed=packed, exact=exact, interpret=interpret,
                           dbg_skip="", chunk=chunk, small=small,
                           quantized=quantized)


def fold_hist(hist_raw: jax.Array, num_features: int,
              num_bins: int, quantized: bool = False) -> jax.Array:
    """Kernel histogram accumulator -> [F, 2, B] f32 (factored or classic
    layout, matching partition_hist_pallas's choice)."""
    if _use_factored(num_features, num_bins, quantized):
        return _fold_factored(hist_raw, num_features, num_bins, quantized)
    f_pad = _padded_features(num_features, num_bins)
    folded = hist_raw[0:2] if quantized else hist_raw[0:2] + hist_raw[2:4]
    return folded.reshape(2, f_pad, num_bins).transpose(1, 0, 2)[:num_features]


def partition_hist_xla(rows: jax.Array, scal, *,
                       num_features: int, num_bins: int, voff: int,
                       bpc: int = 1, packed: bool = False):
    """Reference implementation of the kernel's contract in plain XLA ops
    (full-array mask + cumsum + scatter).  Used by tests and as the
    documentation of the output semantics; the production non-TPU path stays
    on the bucketed-switch builder."""
    assert num_bins >= 32 and num_bins % 32 == 0, \
        "num_bins must be the >=32 kernel-block width (_pad_bins_pow2)"
    n, W = rows.shape
    wb, wc, gcol, thr, dleft, mt, nb, dbin, is_cat, hist_left, use_unfold, \
        eoff = [scal[i] for i in range(12)]
    bitset_words = scal[None, 12:12 + num_bins // 32]
    ri = rows.astype(jnp.int32)
    if packed:
        byte = jnp.take_along_axis(
            ri, jnp.full((n, 1), gcol // 2, jnp.int32), axis=1)[:, 0]
        col = jnp.where(gcol % 2 == 1, (byte >> 4) & 15, byte & 15)
    elif bpc == 2:
        lo = jnp.take_along_axis(ri, jnp.full((n, 1), 2 * gcol, jnp.int32),
                                 axis=1)[:, 0]
        hi = jnp.take_along_axis(ri, jnp.full((n, 1), 2 * gcol + 1,
                                              jnp.int32), axis=1)[:, 0]
        col = lo | (hi << 8)
    else:
        col = jnp.take_along_axis(ri, jnp.full((n, 1), gcol, jnp.int32),
                                  axis=1)[:, 0]
    unfolded = jnp.where((col >= eoff) & (col <= eoff + nb - 2),
                         col - eoff + 1, 0)
    col = jnp.where(use_unfold == 1, unfolded, col)
    is_missing = jnp.where(mt == 1, col == nb - 1,
                           jnp.where(mt == 2, col == dbin, False))
    num_left = jnp.where(is_missing, dleft == 1, col <= thr)
    word = bitset_words[0][jnp.clip(col >> 5, 0, bitset_words.shape[1] - 1)]
    cat_left = ((word.astype(jnp.uint32)
                 >> (col & 31).astype(jnp.uint32)) & 1) == 1
    gl = jnp.where(is_cat == 1, cat_left, num_left)

    iota = jnp.arange(n, dtype=jnp.int32)
    inw = (iota >= wb) & (iota < wb + wc)
    selL = gl & inw
    selR = (~gl) & inw
    nl = jnp.sum(selL, dtype=jnp.int32)
    cl = jnp.cumsum(selL, dtype=jnp.int32)
    cr = jnp.cumsum(selR, dtype=jnp.int32)
    dest = jnp.where(selL, wb + cl - 1,
                     jnp.where(selR, wb + nl + cr - 1, iota))
    rows_new = jnp.zeros_like(rows).at[dest].set(rows, unique_indices=True)

    side = jnp.where(hist_left == 1, selL, selR)
    bins, values = rows_split_xla(rows, num_features, voff, bpc, packed)
    hist = histogram_xla_masked(bins, values * side.astype(jnp.float32)[None],
                                num_bins, jnp.int32(0), jnp.int32(n))
    return rows_new, hist, nl
