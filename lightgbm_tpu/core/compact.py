"""Ensemble compaction: lossy distillation of a trained booster.

Two passes over the host trees, both with *declared* error:

- **leaf-value codebook clustering**: leaves are quantized to a shared
  per-tree-block codebook (uniform grid over the block's leaf range, the
  blocking discipline of :func:`predict_fused.tree_block` so the codebook
  granularity follows the serving layout).  Per-tree error is bounded by
  half the block's grid step; the summed bound over all trees is carried
  in the report as ``declared_max_score_delta``.
- **identical-subtree merging**: after quantization, any split whose left
  and right subtrees are semantically identical (same splits, same routed
  leaf values — weights/counts excluded from the signature) is redundant:
  both branches score every row identically, so the node collapses to one
  merged subtree (weights/counts summed).  This pass is EXACT — it adds
  nothing to the error bound; it converts quantization collisions into
  removed nodes, which shrink ``max(num_leaves)`` and therefore the
  [T, M, L] path matrices every serving dispatch moves.

:func:`compact_booster` mints the result as an immutable generation
through the same text round-trip as ``online.controller._freeze_generation``
(round 17): the distilled booster re-loads from its own model string,
carries the parent's score fingerprints (so score-PSI baselines follow the
swap, same as a retrain), and hot-swaps into a ``ModelRegistry`` like any
other generation.  Every artifact it emits carries measured
``max_score_delta`` / AUC delta / tree+byte reduction, gated by
``tools/perf_gate.py`` against ``PERF_BUDGETS.json``.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .predict import stack_ensemble_host
from .predict_fused import tree_block
from .tree import Tree

# default codebook width: 255 codes ≈ the u8 regime minus a reserved slot;
# fine enough that the summed per-tree bound stays small on shrunk leaves,
# coarse enough that sibling leaves actually collide and merge
DEFAULT_LEAF_CODES = 255


# ---- recursive node form (arrays -> nodes -> arrays) ----

def _extract(tree: Tree, signed: int) -> dict:
    """Tree arrays -> recursive node dicts (``~leaf`` child encoding)."""
    if signed < 0:
        i = ~signed
        return {"leaf": True, "value": float(tree.leaf_value[i]),
                "weight": float(tree.leaf_weight[i]),
                "count": int(tree.leaf_count[i])}
    return {"leaf": False,
            "feature": int(tree.split_feature[signed]),
            "threshold": float(tree.threshold[signed]),
            "dt": int(tree.decision_type[signed]),
            "gain": float(tree.split_gain[signed]),
            "value": float(tree.internal_value[signed]),
            "weight": float(tree.internal_weight[signed]),
            "count": int(tree.internal_count[signed]),
            "l": _extract(tree, int(tree.left_child[signed])),
            "r": _extract(tree, int(tree.right_child[signed]))}


def _sig(node: dict):
    """Semantic signature: routing + leaf values, NOT weights/counts —
    two subtrees with equal signatures score every row identically."""
    if node["leaf"]:
        return ("l", np.float64(node["value"]).tobytes())
    return ("s", node["feature"], np.float64(node["threshold"]).tobytes(),
            node["dt"], _sig(node["l"]), _sig(node["r"]))


def _merge_equal(a: dict, b: dict) -> dict:
    """Merge two signature-equal subtrees: identical structure/values,
    weights and counts summed (the collapsed node's population is the
    union of both branches')."""
    if a["leaf"]:
        return {"leaf": True, "value": a["value"],
                "weight": a["weight"] + b["weight"],
                "count": a["count"] + b["count"]}
    out = dict(a)
    out["weight"] = a["weight"] + b["weight"]
    out["count"] = a["count"] + b["count"]
    out["l"] = _merge_equal(a["l"], b["l"])
    out["r"] = _merge_equal(a["r"], b["r"])
    return out


def _collapse(node: dict) -> dict:
    """Bottom-up identical-subtree merge (exact pass)."""
    if node["leaf"]:
        return node
    node = dict(node)
    node["l"] = _collapse(node["l"])
    node["r"] = _collapse(node["r"])
    if _sig(node["l"]) == _sig(node["r"]):
        return _merge_equal(node["l"], node["r"])
    return node


def _prune_spread(node: dict, tol: float) -> dict:
    """Bounded-spread subtree pruning (lossy, declared): any subtree whose
    leaf values span ≤ ``tol`` collapses to one leaf at the weight-weighted
    mean — every row routed into it moves by at most ``tol/2``.  Bottom-up,
    so the largest prunable subtree wins."""
    if node["leaf"]:
        return node
    node = dict(node)
    node["l"] = _prune_spread(node["l"], tol)
    node["r"] = _prune_spread(node["r"], tol)
    lo, hi, vsum, wsum, weight, count = _agg(node)
    if hi - lo <= tol:
        return {"leaf": True, "value": vsum / wsum,
                "weight": weight, "count": count}
    return node


def _agg(nd: dict):
    """(lo, hi, value_sum*w, w_sum, weight, count) over a subtree's leaves."""
    if nd["leaf"]:
        w = max(nd["weight"], 1e-300)
        return (nd["value"], nd["value"], nd["value"] * w, w,
                nd["weight"], nd["count"])
    lo1, hi1, s1, sw1, w1, c1 = _agg(nd["l"])
    lo2, hi2, s2, sw2, w2, c2 = _agg(nd["r"])
    return (min(lo1, lo2), max(hi1, hi2), s1 + s2, sw1 + sw2,
            w1 + w2, c1 + c2)


def _cap_leaves(node: dict, cap: int) -> Tuple[dict, float]:
    """Collapse minimal-spread subtrees until the tree has ≤ ``cap``
    leaves.  Each collapse replaces a whole subtree by its weighted-mean
    leaf; a row lands in at most one collapsed leaf, so the per-tree error
    bound is half the LARGEST spread collapsed (returned).  This is the
    pass that shrinks ``max(num_leaves)`` across the ensemble — i.e. the
    [T, M, L] path matrices every blocked dispatch moves."""
    worst = 0.0
    while _count_leaves(node) > max(int(cap), 1):
        best = None  # (spread, path) — the cheapest whole-subtree collapse

        def scan(nd, path):
            nonlocal best
            if nd["leaf"]:
                return
            lo, hi, _, _, _, _ = _agg(nd)
            spread = hi - lo
            if best is None or spread < best[0]:
                best = (spread, path)
            scan(nd["l"], path + ("l",))
            scan(nd["r"], path + ("r",))

        scan(node, ())
        if best is None:
            break
        spread, path = best
        worst = max(worst, spread)

        def collapse_at(nd, path):
            if not path:
                lo, hi, vsum, wsum, weight, count = _agg(nd)
                return {"leaf": True, "value": vsum / wsum,
                        "weight": weight, "count": count}
            out = dict(nd)
            out[path[0]] = collapse_at(nd[path[0]], path[1:])
            return out

        node = collapse_at(node, path)
    return node, worst


def _quantize(node: dict, codebook: np.ndarray) -> dict:
    if node["leaf"]:
        i = int(np.argmin(np.abs(codebook - node["value"])))
        out = dict(node)
        out["value"] = float(codebook[i])
        return out
    out = dict(node)
    out["l"] = _quantize(node["l"], codebook)
    out["r"] = _quantize(node["r"], codebook)
    return out


def _count_leaves(node: dict) -> int:
    if node["leaf"]:
        return 1
    return _count_leaves(node["l"]) + _count_leaves(node["r"])


def _rebuild(node: dict, template: Tree) -> Tree:
    """Recursive nodes -> a fresh Tree in LightGBM's index discipline
    (pre-order internal numbering, ``~leaf`` children); categorical
    bitset storage is copied wholesale from the template so cat splits
    keep their ``threshold``-as-cat-index indirection valid."""
    nl = _count_leaves(node)
    t = Tree(max_leaves=nl)
    t.num_leaves = nl
    t.num_cat = template.num_cat
    t.shrinkage = template.shrinkage
    t.cat_boundaries = list(template.cat_boundaries)
    t.cat_threshold = list(template.cat_threshold)
    t.cat_boundaries_inner = list(template.cat_boundaries_inner)
    t.cat_threshold_inner = list(template.cat_threshold_inner)
    if nl == 1:
        t.leaf_value[0] = node["value"]
        t.leaf_weight[0] = node["weight"]
        t.leaf_count[0] = node["count"]
        return t
    counters = {"i": 0, "leaf": 0}

    def build(nd: dict, parent: int) -> int:
        if nd["leaf"]:
            j = counters["leaf"]
            counters["leaf"] += 1
            t.leaf_value[j] = nd["value"]
            t.leaf_weight[j] = nd["weight"]
            t.leaf_count[j] = nd["count"]
            t.leaf_parent[j] = parent
            return ~j
        i = counters["i"]
        counters["i"] += 1
        t.split_feature[i] = nd["feature"]
        t.split_feature_inner[i] = nd["feature"]
        t.threshold[i] = nd["threshold"]
        t.decision_type[i] = nd["dt"]
        t.split_gain[i] = nd["gain"]
        t.internal_value[i] = nd["value"]
        t.internal_weight[i] = nd["weight"]
        t.internal_count[i] = nd["count"]
        t.left_child[i] = build(nd["l"], i)
        t.right_child[i] = build(nd["r"], i)
        return i

    build(node, -1)
    t._recompute_depths()
    return t


# ---- the compaction passes ----

def _ensemble_bytes(trees: List[Tree]) -> int:
    """Device footprint of the stacked raw ensemble (the arrays a serving
    dispatch actually moves) — the denominator of ``byte_reduction``."""
    if not trees:
        return 0
    host = stack_ensemble_host(trees)
    return int(sum(np.asarray(a).nbytes for a in host))


def compact_trees(trees: List[Tree], leaf_codes: int = DEFAULT_LEAF_CODES,
                  merge_subtrees: bool = True, prune_frac: float = 0.0,
                  leaf_cap: Optional[int] = None,
                  block_g: Optional[int] = None
                  ) -> Tuple[List[Tree], Dict]:
    """Cap + prune + quantize + merge ``trees``; returns (new_trees, stats).

    Per tree the lossy budget is half the largest collapsed spread
    (``leaf_cap`` / ``prune_frac`` passes — a row lands in at most one
    collapsed leaf) plus half the codebook grid step (leaf quantization);
    ``stats['declared_max_score_delta']`` sums both bounds over all
    trees.  The *measured* delta the gate checks is computed by
    :func:`measure_compaction` on real rows and can only be tighter."""
    if not trees:
        return [], {"trees": 0, "nodes_in": 0, "nodes_out": 0,
                    "tree_reduction": 0.0, "byte_reduction": 0.0,
                    "model_byte_reduction": 0.0,
                    "declared_max_score_delta": 0.0, "leaf_codes": 0}
    m = max(max(t.num_leaves - 1, 1) for t in trees)
    l = max(t.num_leaves for t in trees)
    g = int(block_g) if block_g else tree_block(len(trees), m, l)
    bytes_in = _ensemble_bytes(trees)
    mbytes_in = sum(len(t.to_string()) for t in trees)
    nodes_in = sum(2 * t.num_leaves - 1 for t in trees)
    out: List[Tree] = []
    declared = 0.0
    for lo in range(0, len(trees), g):
        block = trees[lo:lo + g]
        vals = np.concatenate([t.leaf_value[:t.num_leaves] for t in block])
        vmin, vmax = float(vals.min()), float(vals.max())
        tol = max(prune_frac, 0.0) * (vmax - vmin)
        if leaf_codes > 1 and vmax > vmin:
            codebook = np.linspace(vmin, vmax, int(leaf_codes))
            step = (vmax - vmin) / (int(leaf_codes) - 1)
        else:
            codebook = np.asarray([vmin])
            step = 0.0
        for t in block:
            node = _extract(t, 0 if t.num_leaves > 1 else ~0)
            worst = 0.0
            if tol > 0.0:
                node = _prune_spread(node, tol)
                worst = tol
            if leaf_cap is not None:
                node, capped = _cap_leaves(node, int(leaf_cap))
                worst = max(worst, capped)
            node = _quantize(node, codebook)
            if merge_subtrees:
                node = _collapse(node)
            out.append(_rebuild(node, t))
            declared += step / 2.0 + worst / 2.0
    nodes_out = sum(2 * t.num_leaves - 1 for t in out)
    bytes_out = _ensemble_bytes(out)
    mbytes_out = sum(len(t.to_string()) for t in out)
    stats = {
        "trees": len(trees),
        "nodes_in": int(nodes_in), "nodes_out": int(nodes_out),
        "tree_reduction": (1.0 - nodes_out / nodes_in) if nodes_in else 0.0,
        "bytes_in": int(bytes_in), "bytes_out": int(bytes_out),
        "byte_reduction": (1.0 - bytes_out / bytes_in) if bytes_in else 0.0,
        "model_bytes_in": int(mbytes_in), "model_bytes_out": int(mbytes_out),
        "model_byte_reduction": (1.0 - mbytes_out / mbytes_in)
        if mbytes_in else 0.0,
        "declared_max_score_delta": float(declared),
        "leaf_codes": int(leaf_codes), "prune_frac": float(prune_frac),
        "leaf_cap": int(leaf_cap) if leaf_cap is not None else None,
        "block_g": int(g),
        "max_leaves_in": int(l),
        "max_leaves_out": max((t.num_leaves for t in out), default=1),
    }
    return out, stats


def compact_booster(booster, leaf_codes: int = DEFAULT_LEAF_CODES,
                    merge_subtrees: bool = True, prune_frac: float = 0.0,
                    leaf_cap: Optional[int] = None,
                    block_g: Optional[int] = None):
    """Mint a distilled immutable generation from ``booster``.

    Same machinery as ``online.controller._freeze_generation`` (round 17):
    a text round-trip decouples the distilled booster from the trainer's
    live tree list, then the compacted trees replace the copies through
    the ``models`` setter (which bumps ``_model_gen`` and drops every
    stacked-predictor cache).  Score fingerprints ride along, so a
    registry swap keeps the quality plane's score-PSI baseline — a
    compacted generation republish behaves exactly like a retrain swap."""
    from ..boosting.gbdt import GBDT
    gen = GBDT(booster.config)
    gen.load_model_from_string(booster.save_model_to_string())
    new_trees, stats = compact_trees(gen.models, leaf_codes=leaf_codes,
                                     merge_subtrees=merge_subtrees,
                                     prune_frac=prune_frac,
                                     leaf_cap=leaf_cap, block_g=block_g)
    gen.models = new_trees
    gen.trained_at = getattr(booster, "trained_at", None) or time.time()
    for attr in ("_score_fingerprint_raw", "_score_fingerprint_out",
                 "quality_name"):
        if getattr(booster, attr, None) is not None:
            setattr(gen, attr, getattr(booster, attr))
    return gen, stats


# ---- measurement (feeds the error-budget gate) ----

def _auc(scores: np.ndarray, y: np.ndarray) -> float:
    """Rank AUC (average tie rank) — no external metric dependency."""
    scores = np.asarray(scores, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64) > 0
    npos = int(y.sum())
    nneg = int(y.size - npos)
    if npos == 0 or nneg == 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(len(scores), dtype=np.float64)
    sorted_scores = scores[order]
    i = 0
    while i < len(scores):
        j = i
        while j + 1 < len(scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    return float((ranks[y].sum() - npos * (npos + 1) / 2.0) / (npos * nneg))


def measure_compaction(booster, gen, X: np.ndarray,
                       y: Optional[np.ndarray] = None) -> Dict:
    """Measured deltas of the distilled generation vs its parent on real
    rows: ``max_score_delta`` over raw scores and (with labels) the AUC
    delta — the numbers the perf gate checks against PERF_BUDGETS.json."""
    s_in = np.asarray(booster.predict(X, raw_score=True),
                      dtype=np.float64).reshape(len(X), -1)
    s_out = np.asarray(gen.predict(X, raw_score=True),
                       dtype=np.float64).reshape(len(X), -1)
    rep: Dict = {
        "rows": int(len(X)),
        "max_score_delta": float(np.max(np.abs(s_in - s_out)))
        if len(X) else 0.0,
        "mean_score_delta": float(np.mean(np.abs(s_in - s_out)))
        if len(X) else 0.0,
    }
    if y is not None and s_in.shape[1] == 1:
        auc_in = _auc(s_in[:, 0], y)
        auc_out = _auc(s_out[:, 0], y)
        rep["auc_in"] = auc_in
        rep["auc_out"] = auc_out
        rep["auc_delta"] = abs(auc_in - auc_out)
    return rep
