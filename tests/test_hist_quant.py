"""Round 22: quantized-gradient training — integer histogram operands.

The contract is pinned from both ends, mirroring round 20's precision
tiers: ``hist_precision=exact`` (the default) traces a program with NO
quantization ops in it (the stochastic-rounding hash constants may not
appear in the jaxpr), while the lossy path is deterministic (stateless
(seed, iteration, global row) hash — not noisy), measurably distinct
from exact, within the declared ``quant_*`` budgets, bit-exact across
checkpoint resume (the rounding stream is iteration-clocked, no RNG
state rides the checkpoint), bit-exact between the XLA segment-sum
fallback and the fused Pallas kernels (integer sums ≤ 2^24 are exact in
f32 — parity is equality, not tolerance), and on the parallel learners
the histogram collective narrows to bf16 (pinned on the lowered HLO)
while preserving serial model quality.  The perf gate is pinned
operational: doctored over-budget AND budget-less lossy artifacts FAIL.
"""
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu import obs
from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config
from lightgbm_tpu.core.histogram import (_factored_geometry,
                                         _factored_out_shape,
                                         _hist_channels)
from lightgbm_tpu.core.quant import (GRAD_LEVELS, HESS_LEVELS, _QUANT_TAG,
                                     quant_uniforms, quantize_gradients)
from lightgbm_tpu.core.tree_learner import SerialTreeLearner
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.objective import create_objective

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_off():
    obs.disable()
    yield
    obs.disable()


def _make_data(n=800, features=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, features))
    logit = X[:, 0] * 1.5 - 0.8 * X[:, 1] + np.sin(X[:, 2] * 2.0)
    y = (logit + rng.logistic(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


def _train(hist_precision, n=800, iters=8, seed=7, pallas=False,
           features=8, **extra):
    X, y = _make_data(n=n, features=features)
    cfg = Config(dict(objective="binary", num_leaves=15,
                      min_data_in_leaf=5, learning_rate=0.1,
                      num_iterations=iters, seed=seed, verbosity=-1,
                      hist_precision=hist_precision, **extra))
    ds = BinnedDataset.from_matrix(X, label=y, max_bin=63)
    b = GBDT(cfg, ds, create_objective("binary", cfg))
    if pallas:
        b.learner.use_pallas = True
        b.learner.pallas_interpret = True
    b.train_chunk(iters)
    return np.asarray(b.train_score, np.float32).ravel(), b, X


# ---- exact path unchanged (the non-negotiable) ----

def test_exact_path_jaxpr_has_no_quant_ops():
    """hist_precision=exact traces the SAME program as before the knob
    existed: the stochastic-rounding hash constants (the quant domain tag
    in particular) may not appear anywhere in the jaxpr, and an explicit
    exact config traces byte-identically to the default config."""
    X, y = _make_data(n=512)
    ds = BinnedDataset.from_matrix(X, label=y, max_bin=63)
    grad = jnp.asarray(-(y - y.mean()), jnp.float32)
    hess = jnp.ones((512,), jnp.float32)

    def trace(cfg):
        learner = SerialTreeLearner(ds, cfg)
        return str(jax.make_jaxpr(
            lambda g, h, it: learner.train(g, h, 512, iteration=it))(
                grad, hess, jnp.int32(0)))

    jx_default = trace(Config(num_leaves=15, min_data_in_leaf=5))
    jx_exact = trace(Config(num_leaves=15, min_data_in_leaf=5,
                            hist_precision="exact"))
    jx_quant = trace(Config(num_leaves=15, min_data_in_leaf=5,
                            hist_precision="quantized"))
    tag = str(_QUANT_TAG)
    assert tag not in jx_default and tag not in jx_exact
    assert jx_exact == jx_default, \
        "explicit exact must trace identically to the default config"
    # the knob does something: the quantized trace carries the hash
    assert tag in jx_quant
    assert jx_quant != jx_exact


def test_operand_and_accumulator_geometry():
    """The mechanism: 2 value rows instead of 4, and the factored
    accumulator packs 2x the features per group (total f32 bytes
    layout-invariant — the win is half the MXU group passes)."""
    assert _hist_channels(False) == 4 and _hist_channels(True) == 2
    for F, B in ((20, 256), (32, 64)):
        p_e, g_e = _factored_geometry(F, B, False)
        p_q, g_q = _factored_geometry(F, B, True)
        assert p_q == 2 * p_e
        assert g_q == -(-F // p_q) < g_e
        # with p_q | F there is no group padding, so the total f32
        # accumulator is exactly layout-invariant (the freed channel rows
        # pack 2x the features; the win is the halved group count)
        assert F % p_q == 0
        shp_e = _factored_out_shape(F, B, False)
        shp_q = _factored_out_shape(F, B, True)
        assert shp_e[0] * shp_e[1] == shp_q[0] * shp_q[1]


# ---- the quantizer itself ----

def test_quantizer_integer_exact_zero_pinned_and_stateless():
    rows = jnp.arange(4096, dtype=jnp.int32)
    g = jnp.linspace(-3.0, 3.0, 4096).at[7].set(0.0)
    h = jnp.linspace(0.0, 1.0, 4096).at[7].set(0.0)
    qg, qh, qs = quantize_gradients(g, h, rows, it=3, seed=11)
    qg, qh = np.asarray(qg), np.asarray(qh)
    # exact integers on the declared grids
    np.testing.assert_array_equal(qg, np.round(qg))
    np.testing.assert_array_equal(qh, np.round(qh))
    assert np.abs(qg).max() <= GRAD_LEVELS and qh.min() >= 0
    assert qh.max() <= HESS_LEVELS
    # exact zeros stay exact zero (bagged-out rows get no phantom level)
    assert qg[7] == 0.0 and qh[7] == 0.0
    # stateless: same (seed, it, rows) -> same stream; new it -> new stream
    qg2, _, _ = quantize_gradients(g, h, rows, it=3, seed=11)
    np.testing.assert_array_equal(qg, np.asarray(qg2))
    qg3, _, _ = quantize_gradients(g, h, rows, it=4, seed=11)
    assert not np.array_equal(qg, np.asarray(qg3))
    # uniforms strictly inside [0, 1): a 1.0 would phantom-round zeros
    u = np.asarray(quant_uniforms(rows, 11, 3))
    assert u.min() >= 0.0 and u.max() < 1.0


def test_quantized_rounding_is_unbiased_in_expectation():
    """Stochastic rounding's point: E[q * s] = value.  Averaged over many
    rows of a CONSTANT gradient, the dequantized mean lands within a few
    standard errors of the true value — nearest-rounding would miss by
    the full quantization-step bias."""
    n = 1 << 16
    rows = jnp.arange(n, dtype=jnp.int32)
    val = 0.7321  # deliberately off the 127-level grid
    g = jnp.full((n,), val, jnp.float32)
    h = jnp.full((n,), val, jnp.float32)
    qg, _, qs = quantize_gradients(g, h, rows, it=0, seed=3)
    s_g = float(np.asarray(qs)[0])
    got = float(np.mean(np.asarray(qg))) * s_g
    step = s_g  # one integer level
    se = step / np.sqrt(12.0 * n)
    assert abs(got - val) < 6 * se, (got, val, se)


# ---- determinism, distinctness, budgets ----

def test_quantized_deterministic_distinct_and_budgeted():
    with open(os.path.join(REPO, "PERF_BUDGETS.json")) as fh:
        budgets = json.load(fh)["budgets"]
    s_exact, _, _ = _train("exact")
    s_quant, _, _ = _train("quantized")
    s_quant2, _, _ = _train("quantized")
    np.testing.assert_array_equal(s_quant, s_quant2)
    delta = float(np.max(np.abs(s_exact - s_quant)))
    assert 0.0 < delta <= budgets["quant_max_score_delta"]


def test_quantized_grad_alias_and_validation():
    from lightgbm_tpu.utils.log import LightGBMError
    cfg = Config(dict(quantized_grad="quantized"))
    assert cfg.hist_precision == "quantized"
    with pytest.raises(LightGBMError):
        Config(dict(hist_precision="int8"))


# ---- resume: the rounding stream is iteration-clocked ----

def test_resume_bit_exact_quantized(tmp_path):
    """train(N) vs train(k) -> kill -> resume -> N, byte-identical model
    strings: no RNG state rides the checkpoint, so the resumed run must
    replay the identical stochastic-rounding stream (the same contract
    the bagging mask holds in test_checkpoint.py)."""
    X, y = _make_data(n=600)

    def build(snapshot_freq=-1):
        cfg = Config(dict(objective="binary", num_leaves=15,
                          min_data_in_leaf=5, num_iterations=12,
                          seed=7, verbosity=-1, snapshot_freq=snapshot_freq,
                          hist_precision="quantized",
                          bagging_fraction=0.8, bagging_freq=3))
        ds = BinnedDataset.from_matrix(X, label=y, max_bin=63)
        return create_boosting(cfg.boosting, cfg, ds,
                               create_objective("binary", cfg))

    out = str(tmp_path / "model.txt")
    full = build(snapshot_freq=5)
    full.train(snapshot_out=out)
    resumed = build(snapshot_freq=5)
    it = resumed.resume_from_checkpoint(out)
    assert 0 < it < 12
    resumed.train()
    assert full.save_model_to_string() == resumed.save_model_to_string()


# ---- backend parity: integer sums make it bit-exact ----

def test_backend_bit_exact_xla_vs_pallas_interpret():
    """Quantized histogram sums are small integers in f32, so the XLA
    segment-sum fallback and the fused Pallas kernels (interpret off-TPU)
    must agree np.array_equal at full-train granularity — any epsilon
    would mean a backend is not accumulating the same integers."""
    kw = dict(n=4096, iters=2, features=6)  # CHUNK-aligned: fused engages
    s_fb, _, _ = _train("quantized", **kw)
    s_pl, _, _ = _train("quantized", pallas=True, **kw)
    np.testing.assert_array_equal(s_fb, s_pl)


# ---- parallel: the collective narrows to bf16 ----

def _parallel_learner(hist_precision, d=8):
    from lightgbm_tpu.parallel import DataParallelTreeLearner, default_mesh
    rng = np.random.RandomState(0)
    n, F = 1024, 16
    X = rng.normal(size=(n, F))
    y = X[:, 0] + rng.normal(scale=0.1, size=n)
    ds = BinnedDataset.from_matrix(X, label=y, max_bin=15)
    cfg = Config(num_leaves=8, min_data_in_leaf=2,
                 hist_precision=hist_precision)
    learner = DataParallelTreeLearner(ds, cfg, mesh=default_mesh(d))
    grad = learner.pad_rows(jnp.asarray(-(y - y.mean()), jnp.float32))
    hess = learner.pad_rows(jnp.ones((n,), jnp.float32))
    fm = jnp.ones((learner.feat.num_bin.shape[0],), bool)
    txt = learner._build_fn.lower(
        learner.bins, grad, hess, jnp.int32(n), fm, learner.feat,
        jnp.int32(0)).as_text()
    return txt


def _collective_blobs(txt, op):
    lines = txt.splitlines()
    return [" ".join(lines[i:i + 8]) for i, ln in enumerate(lines)
            if op in ln]


def test_parallel_hist_collective_is_bf16():
    """On the lowered data-parallel program, every histogram
    reduce_scatter rides a bf16 payload under quantized (HALF the f32
    collective bytes) — and stays f32 under exact."""
    txt_q = _parallel_learner("quantized")
    txt_e = _parallel_learner("exact")
    rs_q = _collective_blobs(txt_q, "reduce_scatter")
    rs_e = _collective_blobs(txt_e, "reduce_scatter")
    assert rs_q and rs_e, "histogram reduce_scatter missing from HLO"
    assert all("bf16" in b for b in rs_q), \
        "quantized hist collective must ride bf16"
    assert all("bf16" not in b for b in rs_e), \
        "exact hist collective must stay f32"


def test_parallel_quantized_quality_matches_serial():
    """End-to-end data-parallel quantized training holds serial-quantized
    model quality: the bf16 psum rounds the integer sums (charged to the
    quant budgets), so the pin is training-loss parity, not bit equality
    — same form as test_parallel's psum reduction-order allowance, but
    wider: a bf16-rounded bin sum can flip a near-tie split, changing
    WHICH tree is grown (observed ~2% l2 wobble either direction at this
    scale), so the band pins quality-holds, not tree-identity."""
    scores = {}
    for lt in ("serial", "data"):
        rng = np.random.RandomState(7)
        X = rng.normal(size=(4000, 11))
        y = X[:, 0] * 1.5 + np.nan_to_num(X[:, 1]) ** 2 \
            + rng.normal(scale=0.1, size=4000)
        ds = BinnedDataset.from_matrix(X, label=y, max_bin=63)
        cfg = Config(objective="regression", tree_learner=lt,
                     num_leaves=7, num_iterations=5, learning_rate=0.2,
                     hist_precision="quantized", seed=7)
        b = GBDT(cfg, ds, create_objective("regression", cfg))
        for _ in range(5):
            b.train_one_iter()
        pred = np.asarray(b.train_score[0, :ds.num_data])
        scores[lt] = float(np.mean((np.asarray(ds.metadata.label)
                                    - pred) ** 2))
    assert scores["data"] == pytest.approx(scores["serial"], rel=5e-2)


# ---- the gate is operational: doctored artifacts FAIL ----

def test_perf_gate_fails_doctored_and_budget_less_artifacts(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import perf_gate
    finally:
        sys.path.pop(0)
    src = os.path.join(REPO, "BENCH_hist_quant_interp.json")
    budgets = os.path.join(REPO, "PERF_BUDGETS.json")
    with open(src) as fh:
        doc = json.load(fh)
    with open(budgets) as fh:
        bspec = json.load(fh)
    # the committed artifact passes as-is
    assert perf_gate.run_gate([src], budgets) == 0
    # doctor 1: score delta over budget
    bad = json.loads(json.dumps(doc))
    bad["quant"]["max_score_delta"] = \
        bspec["budgets"]["quant_max_score_delta"] * 2.0
    p1 = str(tmp_path / "over_delta.json")
    with open(p1, "w") as fh:
        json.dump(bad, fh)
    assert perf_gate.run_gate([p1], budgets) == 1
    # doctor 2: non-deterministic or backend-divergent artifacts fail
    for field in ("deterministic", "backend_bit_exact"):
        bad = json.loads(json.dumps(doc))
        bad["quant"][field] = False
        p = str(tmp_path / ("no_%s.json" % field))
        with open(p, "w") as fh:
            json.dump(bad, fh)
        assert perf_gate.run_gate([p], budgets) == 1
    # doctor 3: a lossy path with NO declared budget line fails loudly —
    # strip the quant budgets from a copy of PERF_BUDGETS.json
    stripped = json.loads(json.dumps(bspec))
    for k in list(stripped["budgets"]):
        if k.startswith("quant_"):
            del stripped["budgets"][k]
    b2 = str(tmp_path / "budgets_no_quant.json")
    with open(b2, "w") as fh:
        json.dump(stripped, fh)
    assert perf_gate.run_gate([src], b2) == 1
    # unknown artifacts are a hard error naming the file (registry rule)
    p4 = str(tmp_path / "mystery.json")
    with open(p4, "w") as fh:
        json.dump({"something": "else"}, fh)
    assert perf_gate.run_gate([p4], budgets) == 2
