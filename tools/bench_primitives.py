"""Reliable (chained fori_loop) benchmarks of the primitives the windowed
tree-build redesign depends on: row gather/scatter, argsort, cumsum, and
histogram kernel variants (bf16, 2-features-per-lane-group packing).

Usage: python tools/bench_primitives.py [--rows N] [--reps R]
"""
import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

F = 28
B = 128
NT = 1024


def kern_base(bins_ref, vals_ref, out_ref, *, nf, nb, dt):
    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)
    b = bins_ref[...].astype(jnp.int32)
    v = vals_ref[...].astype(dt)
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, nb), 1)
    for f in range(nf):
        oh = (b[:, f:f + 1] == iota).astype(dt)
        acc = jax.lax.dot_general(v, oh, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        out_ref[f, :, :] += acc


def kern_pack2(bins_ref, vals_ref, out_ref, *, nf, nb, dt):
    """Two 64-bin features share one 128-lane one-hot (OR of two compares),
    halving the MXU streams — the TPU version of the reference GPU's
    4-features-per-DWORD packing (gpu_tree_learner.cpp:317-344)."""
    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)
    b = bins_ref[...].astype(jnp.int32)
    v = vals_ref[...].astype(dt)
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    for p in range(nf // 2):
        c0 = b[:, 2 * p:2 * p + 1]
        c1 = b[:, 2 * p + 1:2 * p + 2] + 64
        oh = ((c0 == iota) | (c1 == iota)).astype(dt)
        acc = jax.lax.dot_general(v, oh, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        out_ref[p, :, :] += acc


@functools.partial(jax.jit, static_argnames=("kern", "dt", "fo"))
def hist(bins, vals, kern, dt, fo):
    n, f = bins.shape
    k = functools.partial(kern, nf=f, nb=B, dt=dt)
    return pl.pallas_call(
        k, grid=(n // NT,),
        in_specs=[pl.BlockSpec((NT, f), lambda i: (i, 0)),
                  pl.BlockSpec((NT, 2), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((fo, 2, 128), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((fo, 2, 128), jnp.float32),
    )(bins, vals)


def fetch(x):
    return float(jax.device_get(jnp.ravel(x)[0]))


def main():
    ap = argparse.ArgumentParser(
        description="chained-fori_loop primitive benchmarks (gather/"
                    "scatter/sort/cumsum + histogram kernel variants)")
    ap.add_argument("--rows", type=int, default=2_097_152)
    ap.add_argument("--reps", type=int, default=20)
    args = ap.parse_args()
    n, reps = args.rows, args.reps

    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, 63, size=(n, F), dtype=np.uint8))
    vals = jnp.asarray(rng.normal(size=(n, 2)).astype(np.float32))
    perm = jnp.asarray(rng.permutation(n).astype(np.int32))
    leaf = jnp.asarray(rng.randint(0, 64, size=(n,), dtype=np.int32))

    f_lat = jax.jit(lambda x: x + 1.0)
    fetch(f_lat(jnp.float32(0)))
    t0 = time.perf_counter()
    for _ in range(5):
        fetch(f_lat(jnp.float32(0)))
    lat = (time.perf_counter() - t0) / 5
    print(f"tunnel latency ~{lat*1e3:.1f} ms", flush=True)

    def chain(step, init):
        @jax.jit
        def run(state):
            return jax.lax.fori_loop(0, reps, lambda i, s: step(s), state)
        out = run(init)
        fetch(jax.tree_util.tree_leaves(out)[0])
        t0 = time.perf_counter()
        out = run(init)
        fetch(jax.tree_util.tree_leaves(out)[0])
        return (time.perf_counter() - t0 - lat) / reps

    def report(name, secs):
        print(f"{name:58s} {secs*1e3:8.2f} ms {n/secs/1e6:9.1f} Mrows/s",
              flush=True)

    def guard(name, fn):
        try:
            report(name, fn())
        except Exception as e:  # noqa: BLE001
            print(f"{name:58s} FAILED: {str(e)[:140]}", flush=True)

    # ---- data movement ----
    guard("take rows bins[perm] [N,28]u8",
          lambda: chain(lambda s: (s[0][s[1]], s[1]), (bins, perm)))
    guard("take vals[perm] [N,2]f32",
          lambda: chain(lambda s: (s[0][s[1]] * 1.0000001, s[1]),
                        (vals, perm)))
    guard("take idx perm[perm] [N]i32",
          lambda: chain(lambda s: (s[0][s[1]], s[1]), (perm, perm)))
    guard("scatter rows zeros.at[perm].set(bins)",
          lambda: chain(
              lambda s: (jnp.zeros_like(s[0]).at[s[1]].set(s[0]) | s[0][0, 0],
                         s[1]), (bins, perm)))
    guard("scatter idx zeros.at[perm].set(iota)",
          lambda: chain(
              lambda s: (jnp.zeros_like(s[0]).at[s[0]].set(s[1])
                         + s[0][0] * 0, s[1]),
              (perm, jnp.arange(n, dtype=jnp.int32))))
    guard("argsort leaf [N]i32",
          lambda: chain(lambda s: (jnp.argsort(s[0] ^ s[1]), s[1] ^ 1),
                        (leaf, jnp.int32(0))))
    guard("sort u64 keys [N]",
          lambda: chain(lambda s: (jnp.sort(s[0]) + s[0][0] % 2, s[1]),
                        (leaf.astype(jnp.uint32), jnp.int32(0))))
    guard("cumsum i32 [N]",
          lambda: chain(lambda s: jnp.cumsum(s % 3, dtype=jnp.int32),
                        jnp.ones((n,), jnp.int32)))

    # ---- histogram kernel variants ----
    def bench_hist(name, kern, dt, fo):
        def step(s):
            v, acc = s
            h = hist(bins, v, kern, dt, fo)
            return v + h[0, 0, 0] * 1e-30, acc + h[0, 0, 0]
        guard(name, lambda: chain(step, (vals, jnp.float32(0))))

    bench_hist("hist f32 per-feature (baseline)", kern_base, jnp.float32, F)
    bench_hist("hist bf16 per-feature", kern_base, jnp.bfloat16, F)
    bench_hist("hist f32 packed-2 (64-bin pairs)", kern_pack2, jnp.float32,
               F // 2)
    bench_hist("hist bf16 packed-2 (64-bin pairs)", kern_pack2, jnp.bfloat16,
               F // 2)


if __name__ == "__main__":
    main()
