"""End-of-run telemetry summary: JSON artifact + human table.

The summary is shaped like the BENCH_r*.json trajectory entries this repo's
perf history uses (``metric``/``value``/``unit`` headline + named
sub-sections), so ``bench.py``, ``tools/head_to_head.py`` and the PERF.md
hardware protocols can consume a telemetry artifact directly: one flag
(``telemetry_out=...``) turns ANY run into a BENCH artifact.

Layout::

    {
      "v": 1, "metric": "telemetry_run", "unit": "row-trees/s",
      "value": <overall row-trees/s or null>,
      "iterations": N, "rows": N, "wall_s": ...,
      "rows_per_s": {histogram summary},        # per-chunk training rate
      "ns_per_row": {histogram summary},
      "host_phases": {"scope": seconds, ...},   # global_timer snapshot
      "counters": {...}, "gauges": {...}, "histograms": {...},
      "recompiles": {"fn|bucket": n}, "recompile_total": n,
      "resilience": {"preemptions": n, "io_retries": n,
                     "predict_fallbacks": n, "checkpoint_skipped": n,
                     "preempt_checkpoint_s": {histogram summary},
                     "watchdog_stall_s": x|null},
      "serving": {"models": {name: {"requests": n, "rows": n, "qps": x|null,
                                    "latency_s": {histogram summary},
                                    "occupancy": {histogram summary},
                                    "fallbacks": n}},
                  "batches": n, "single_row_fast": n, "rejected": n,
                  "evictions": n, "swaps": n, "readmits": n,
                  "queue_depth": {histogram summary},
                  "wall_s": x|null},             # only when the run served
      "mfu": x|null, "device_util": y|null,
      "events": <event count>
    }
"""
from __future__ import annotations

import json
from typing import Any, Dict, Optional

from . import launches, recompile
from .registry import EVENT_SCHEMA_VERSION, Telemetry

_SERVE_REQ = "serve_requests_model_"
_SERVE_ROWS = "serve_rows_model_"
_SERVE_LAT = "serve_latency_s_model_"
_SERVE_OCC = "serve_occupancy_model_"
_SERVE_FB = "predict_fallbacks_model_"
_SERVE_PREC_REQ = "serve_requests_precision_"
_SERVE_PREC_ROWS = "serve_rows_precision_"


def serving_block(counters: Dict[str, Any], gauges: Dict[str, Any],
                  hists: Dict[str, Any]):
    """Fold the serving tier's per-model metrics into one summary section
    (None when the run never served).  Shared by :func:`summarize` and
    ``tools/obs_report.py``'s died-run recovery path."""
    models: Dict[str, Dict[str, Any]] = {}

    def m(name):
        return models.setdefault(name, {})

    for name, n in counters.items():
        if name.startswith(_SERVE_REQ):
            m(name[len(_SERVE_REQ):])["requests"] = int(n)
        elif name.startswith(_SERVE_ROWS):
            m(name[len(_SERVE_ROWS):])["rows"] = int(n)
        elif name.startswith(_SERVE_FB):
            m(name[len(_SERVE_FB):])["fallbacks"] = int(n)
    for name, h in hists.items():
        if name.startswith(_SERVE_LAT):
            m(name[len(_SERVE_LAT):])["latency_s"] = h
        elif name.startswith(_SERVE_OCC):
            m(name[len(_SERVE_OCC):])["occupancy"] = h
    if not models and not counters.get("serve_batches") \
            and not counters.get("serve_rejected") \
            and not counters.get("serve_failed"):
        # rejected/failed-only runs still get a block: a fully saturated
        # deployment is exactly when the backpressure counters matter
        return None
    wall = gauges.get("serve_wall_s")
    for info in models.values():
        req = info.get("requests")
        info["qps"] = (req / wall) if (req and wall) else None
    # precision-tier traffic split (round 20): which share of the served
    # requests/rows rode the lossy bf16 tier vs exact.  Keyed per tier;
    # an all-exact run shows {"exact": ...} only
    precisions: Dict[str, Dict[str, int]] = {}
    for name, n in counters.items():
        if name.startswith(_SERVE_PREC_REQ):
            precisions.setdefault(name[len(_SERVE_PREC_REQ):],
                                  {})["requests"] = int(n)
        elif name.startswith(_SERVE_PREC_ROWS):
            precisions.setdefault(name[len(_SERVE_PREC_ROWS):],
                                  {})["rows"] = int(n)
    return {
        "models": models,
        "precisions": precisions,
        # the never-drop invariant (Server.close records it; None on runs
        # that died before close — the counters above still reconstruct)
        "dropped": gauges.get("serve_dropped"),
        "batches": int(counters.get("serve_batches", 0)),
        "single_row_fast": int(counters.get("serve_single_row_fast", 0)),
        "rejected": int(counters.get("serve_rejected", 0)),
        "failed": int(counters.get("serve_failed", 0)),
        "evictions": int(counters.get("serve_evictions", 0)),
        "swaps": int(counters.get("serve_swaps", 0)),
        "readmits": int(counters.get("serve_readmits", 0)),
        "queue_depth": hists.get("serve_queue_depth", {"count": 0}),
        "wall_s": wall,
    }


_ONLINE_TRIG = "online_trigger_"


def online_block(counters: Dict[str, Any], gauges: Dict[str, Any],
                 hists: Dict[str, Any]):
    """Fold the online controller's metrics into one summary section
    (None when the run never trained while serving).  Shared by
    :func:`summarize` and ``tools/obs_report.py``'s died-run recovery."""
    cycles = counters.get("online_cycles")
    if not cycles:
        return None
    return {
        "cycles": int(cycles),
        "generation": gauges.get("online_generation"),
        "rows_behind": gauges.get("online_rows_behind"),
        "triggers": {name[len(_ONLINE_TRIG):]: int(n)
                     for name, n in sorted(counters.items())
                     if name.startswith(_ONLINE_TRIG)},
        "train_s": hists.get("online_train_s", {"count": 0}),
        "publish_s": hists.get("online_publish_s", {"count": 0}),
    }


_CONTRIB_LAT = "contrib_latency_s_bucket_"


def contrib_block(counters: Dict[str, Any], gauges: Dict[str, Any],
                  hists: Dict[str, Any]):
    """Fold the explanations plane (round 19 ``pred_contrib``) into one
    summary section: device contrib dispatches/rows, per-shape-bucket
    latency histograms, serving-tier contrib request count and degraded
    fallbacks.  None when the run never served contributions.  Shared by
    :func:`summarize` and ``tools/obs_report.py``'s died-run recovery."""
    calls = int(counters.get("contrib_calls", 0))
    reqs = int(counters.get("serve_contrib_requests", 0))
    fbs = int(counters.get("contrib_fallbacks", 0))
    if not calls and not reqs and not fbs:
        # fallbacks alone still get a block: a run whose EVERY contrib
        # call degraded at the booster level (calls==0) is exactly when
        # the fallbacks signal matters most
        return None
    del gauges  # symmetry with the sibling *_block helpers
    return {
        "calls": calls,
        "rows": int(counters.get("contrib_rows", 0)),
        "serve_requests": reqs,
        "fallbacks": fbs,
        "latency_s": {name[len(_CONTRIB_LAT):]: h
                      for name, h in sorted(hists.items())
                      if name.startswith(_CONTRIB_LAT)},
    }


def ingest_block(counters: Dict[str, Any], gauges: Dict[str, Any],
                 hists: Dict[str, Any]):
    """Fold the streaming loader's metrics (round 21, io/loader.py
    ``_load_streaming``) into one summary section: chunk/row counts, the
    per-chunk binning throughput histogram, pipeline stall time (wall the
    consumer spent waiting on the parse thread — the overlap the 2-deep
    pipeline failed to hide) and the host RSS high-water that makes the
    bounded-memory claim scrapeable.  None when the run never streamed.
    Shared by :func:`summarize` and ``tools/obs_report.py``'s died-run
    recovery."""
    chunks = counters.get("ingest_chunks")
    if not chunks:
        return None
    return {
        "chunks": int(chunks),
        "rows": int(counters.get("ingest_rows", 0)),
        "rows_per_s": hists.get("ingest_chunk_rows_per_s", {"count": 0}),
        "stall_ms": gauges.get("ingest_stall_ms"),
        "rss_high_water_bytes": gauges.get("host_rss_high_water_bytes"),
    }


def quant_block(counters: Dict[str, Any], gauges: Dict[str, Any],
                hists: Dict[str, Any]):
    """Fold the quantized-gradient training facts (round 22,
    core/quant.py) into one summary section: how many chunks/iterations
    rode the integer-histogram path and its static geometry (grad/hess
    levels, 2-row operand channels).  None when the run trained exact.
    Shared by :func:`summarize` and ``tools/obs_report.py``'s died-run
    recovery."""
    chunks = counters.get("quant_chunks")
    if not chunks:
        return None
    del hists  # symmetry with the sibling *_block helpers
    return {
        "chunks": int(chunks),
        "iterations": int(counters.get("quant_iters", 0)),
        "grad_levels": gauges.get("quant_grad_levels"),
        "hess_levels": gauges.get("quant_hess_levels"),
        "hist_channels": gauges.get("quant_hist_channels"),
    }


def summarize(tele: Telemetry, extra: Optional[Dict[str, Any]] = None
              ) -> Dict[str, Any]:
    """Fold a run's registry + recompile counters into the summary dict."""
    from ..utils.timer import global_timer
    snap = tele.registry.snapshot()
    hists = snap["histograms"]
    gauges = snap["gauges"]
    rows = gauges.get("train_rows")
    iters = gauges.get("train_iterations")
    wall = gauges.get("train_wall_s")
    rows = int(rows) if rows is not None else None
    iters = int(iters) if iters is not None else None
    value = None
    if rows and iters and wall:
        value = rows * iters / wall
    # host phases scoped to THIS run: global_timer totals minus the
    # snapshot taken when the Telemetry was constructed (a second run in
    # the same process must not inherit the first run's scope time)
    base = getattr(tele, "timer_baseline", {})
    phases = {}
    for name, tot in global_timer.totals().items():
        delta = tot - base.get(name, 0.0)
        if delta > 1e-9:
            phases[name] = delta
    # recompiles likewise scoped to THIS run (an obs.recompile.reset()
    # after the baseline — bench/dryrun warmup — only shrinks counts, so
    # missing/negative deltas clamp to the post-reset values)
    rc_base = getattr(tele, "recompile_baseline", {})
    run_recompiles = {}
    for key, n in recompile.counts().items():
        delta = n - rc_base.get(key, 0)
        if delta > 0:
            run_recompiles["%s|%s" % key] = delta
    # split-kernel launch accounting (round 12), likewise run-scoped: total
    # launches and launches-per-tree attributed per growth mode so the
    # leaf-wise L-1 vs level-wise depth*classes structure reads off the
    # artifact directly
    lb = getattr(tele, "launch_baseline", {})
    tb = getattr(tele, "launch_tree_baseline", {})
    run_launches = {}
    launch_total = 0
    for mode, n in launches.counts().items():
        dl = n - lb.get(mode, 0)
        dt = launches.trees().get(mode, 0) - tb.get(mode, 0)
        if dl > 0:
            run_launches[mode] = {
                "launches": dl, "trees": dt,
                "per_tree": (dl / dt) if dt else None}
            launch_total += dl
    # resilience rollup (lightgbm_tpu/resilience.py): every fault the run
    # absorbed, as one named subsection — the drill report reads this
    counters = snap["counters"]
    resilience = {
        "preemptions": int(counters.get("preemptions", 0)),
        "io_retries": int(counters.get("io_retries", 0)),
        "predict_fallbacks": int(counters.get("predict_fallbacks", 0)),
        "checkpoint_skipped": int(counters.get("checkpoint_skipped", 0)),
        "preempt_checkpoint_s": hists.get("preempt_checkpoint_s",
                                          {"count": 0}),
        "watchdog_stall_s": gauges.get("watchdog_stall_s"),
    }
    out: Dict[str, Any] = {
        "v": EVENT_SCHEMA_VERSION,
        "metric": "telemetry_run",
        "unit": "row-trees/s",
        "value": value,
        "iterations": iters,
        "rows": rows,
        "wall_s": wall,
        "rows_per_s": hists.get("chunk_rows_per_s", {"count": 0}),
        "ns_per_row": hists.get("chunk_ns_per_row", {"count": 0}),
        "host_phases": phases,
        "counters": snap["counters"],
        "gauges": gauges,
        "histograms": hists,
        "recompiles": run_recompiles,
        "recompile_total": sum(run_recompiles.values()),
        "tree_kernel_launches": run_launches,
        "tree_kernel_launch_total": launch_total,
        "resilience": resilience,
        "mfu": gauges.get("mfu"),
        "device_util": gauges.get("device_util"),
        "events": getattr(tele, "event_count", len(tele.events)),
        # pod provenance: which host produced this summary (rank None =
        # single-process run)
        "rank": getattr(tele, "rank", None),
        "host": getattr(tele, "host", None),
    }
    # serving rollup (lightgbm_tpu/serving): per-model qps/latency/occupancy
    # plus eviction/swap counts — present only when the run served traffic
    serving = serving_block(counters, gauges, hists)
    if serving is not None:
        out["serving"] = serving
    # online-learning rollup (lightgbm_tpu/online): train-while-serve
    # cycles by trigger, the live generation and the rows-behind gauge —
    # present only when the run ran a controller
    online = online_block(counters, gauges, hists)
    if online is not None:
        out["online"] = online
    # explanations rollup (round 19, core/predict_contrib.py): contrib
    # dispatch/row counts, per-bucket latency and degraded fallbacks —
    # present only when the run served pred_contrib traffic
    contrib = contrib_block(counters, gauges, hists)
    if contrib is not None:
        out["contrib"] = contrib
    # streaming-ingest rollup (round 21, io/loader.py): chunks, binning
    # throughput, pipeline stall and the host RSS high-water — present
    # only when the run streamed its dataset
    ingest = ingest_block(counters, gauges, hists)
    if ingest is not None:
        out["ingest"] = ingest
    # quantized-training rollup (round 22, core/quant.py): present only
    # when the run trained with hist_precision=quantized
    quant = quant_block(counters, gauges, hists)
    if quant is not None:
        out["quant"] = quant
    # performance-forensics rollups (round 16), each present only when its
    # run-owned state exists: compile wall-seconds per (fn, bucket) — the
    # autotuner's ranking substrate — device-memory high-water, profiler
    # captures and the live-alert tally
    acct = getattr(tele, "compile_acct", None)
    if acct is not None:
        comp = acct.snapshot()
        if comp:
            out["compile"] = comp
    from . import devmem as _devmem
    dm = _devmem.snapshot(tele)
    if dm:
        out["devmem"] = dm
    from . import profiling as _profiling
    prof = _profiling.snapshot(tele)
    if prof:
        out["profiling"] = prof
    eng = getattr(tele, "alerts", None)
    if eng is not None:
        out["alerts"] = eng.snapshot()
    elif snap["counters"].get("alerts_fired"):
        # out-of-band incidents (watchdog stall without an engine) still
        # surface a tally so perf_gate's alerts_fired budget sees them
        out["alerts"] = {"enabled": False, "series": [],
                         "fired_total": int(snap["counters"]
                                            ["alerts_fired"])}
    # kernel-plan provenance (round 18, lightgbm_tpu/plan): which planner
    # produced the dispatch shapes behind this artifact's numbers —
    # analytic | tuned | pinned per site, plus the engaged cache and the
    # always-on fallback counter.  BENCH artifacts carry this so a tuned
    # number is never mistaken for an analytic one (perf_gate checks it).
    stamps = getattr(tele, "plan_stamps", None)
    if stamps:
        from ..plan import cache as _plan_cache
        from ..plan import state as _plan_state
        sites = {site: {k: v for k, v in info.items() if k != "_tag"}
                 for site, info in stamps.items()}
        provs = {info["provenance"] for info in sites.values()}
        headline = ("pinned" if "pinned" in provs
                    else "tuned" if "tuned" in provs else "analytic")
        out["plan"] = {
            "provenance": headline,
            "sites": sites,
            "cache_path": _plan_state.configured_path(),
            "cache_fallbacks": _plan_cache.fallback_count(),
        }
    # model-quality rollup (obs/quality.py): per-model drift PSI/JS ranked
    # by importance, score PSI, generation + freshness — present only when
    # the run monitored traffic
    mon = getattr(tele, "quality", None)
    if mon is not None:
        q = mon.snapshot()
        if q:
            out["quality"] = q
    if extra:
        out.update(extra)
    return out


def human_table(summary: Dict[str, Any]) -> str:
    """Render a summary dict as the end-of-run report table."""
    lines = ["telemetry summary"]

    def row(k, v):
        lines.append("  %-34s %s" % (k, v))

    def num(v, fmt="%.6g"):
        return "-" if v is None else (fmt % v)

    row("row-trees/s", num(summary.get("value"), "%.1f"))
    row("iterations", num(summary.get("iterations"), "%d")
        if summary.get("iterations") is not None else "-")
    row("wall_s", num(summary.get("wall_s")))
    row("mfu", num(summary.get("mfu")))
    row("device_util", num(summary.get("device_util")))
    row("recompiles (total)", "%d" % summary.get("recompile_total", 0))
    for key, n in sorted((summary.get("recompiles") or {}).items()):
        row("  recompile %s" % key, "%d" % n)
    if summary.get("tree_kernel_launch_total"):
        row("tree kernel launches (total)",
            "%d" % summary["tree_kernel_launch_total"])
        for mode, d in sorted((summary.get("tree_kernel_launches")
                               or {}).items()):
            per = d.get("per_tree")
            row("  launches[%s]" % mode,
                "%d over %d trees (%s/tree)"
                % (d.get("launches", 0), d.get("trees", 0),
                   "-" if per is None else "%.1f" % per))
    srv = summary.get("serving") or {}
    if srv:
        lines.append("  serving:")
        for name, info in sorted((srv.get("models") or {}).items()):
            lat = info.get("latency_s") or {}
            occ = info.get("occupancy") or {}
            row("    model %s" % name,
                "req=%d rows=%d qps=%s p50=%s p99=%s occ=%s fb=%d"
                % (info.get("requests", 0), info.get("rows", 0),
                   "-" if info.get("qps") is None else "%.1f" % info["qps"],
                   "-" if not lat.get("count") else "%.6g" % lat["p50"],
                   "-" if not lat.get("count") else "%.6g" % lat["p99"],
                   "-" if not occ.get("count") else "%.2f" % occ["p50"],
                   info.get("fallbacks", 0)))
        row("    batches", "%d (single-row fast %d)"
            % (srv.get("batches", 0), srv.get("single_row_fast", 0)))
        prec = srv.get("precisions") or {}
        if prec:
            row("    precision tiers",
                " ".join("%s: req=%d rows=%d"
                         % (tier, info.get("requests", 0),
                            info.get("rows", 0))
                         for tier, info in sorted(prec.items())))
        qd = srv.get("queue_depth") or {}
        if qd.get("count"):
            row("    queue depth", "p50=%.6g p99=%.6g"
                % (qd.get("p50", float("nan")), qd.get("p99", float("nan"))))
        row("    evictions/swaps/readmits", "%d/%d/%d"
            % (srv.get("evictions", 0), srv.get("swaps", 0),
               srv.get("readmits", 0)))
        if srv.get("rejected") or srv.get("failed"):
            row("    rejected/failed", "%d/%d"
                % (srv.get("rejected", 0), srv.get("failed", 0)))
    qual = summary.get("quality") or {}
    if qual.get("models"):
        lines.append("  quality:")
        for name, info in sorted(qual["models"].items()):
            row("    model %s" % name,
                "gen=%s rows=%d level=%s psi_max=%s@%s score_psi=%s "
                "behind=%ss/%srows"
                % (info.get("generation"), info.get("rows", 0),
                   info.get("level", "ok"),
                   "-" if info.get("psi_max") is None
                   else "%.4f" % info["psi_max"],
                   info.get("feature_max") or "-",
                   "-" if info.get("score_psi") is None
                   else "%.4f" % info["score_psi"],
                   "-" if info.get("seconds_behind") is None
                   else "%.0f" % info["seconds_behind"],
                   "-" if info.get("rows_behind") is None
                   else "%d" % info["rows_behind"]))
            for f in (info.get("features") or [])[:5]:
                row("      %s" % f.get("name"),
                    "psi=%.4f js=%.4f imp=%.4f"
                    % (f.get("psi", 0.0), f.get("js", 0.0),
                       f.get("importance", 0.0)))
    onl = summary.get("online") or {}
    if onl:
        lines.append("  online:")
        trig = onl.get("triggers") or {}
        row("    cycles", "%d (%s) gen=%s rows_behind=%s"
            % (onl.get("cycles", 0),
               ", ".join("%s=%d" % kv for kv in sorted(trig.items()))
               or "-",
               onl.get("generation"),
               onl.get("rows_behind")))
        for key in ("train_s", "publish_s"):
            h = onl.get(key) or {}
            if h.get("count"):
                row("    " + key, "n=%d p50=%.6g p99=%.6g"
                    % (h["count"], h.get("p50", float("nan")),
                       h.get("p99", float("nan"))))
    ctb = summary.get("contrib") or {}
    if ctb:
        lines.append("  contrib:")
        row("    calls/rows", "%d/%d (serve requests %d, fallbacks %d)"
            % (ctb.get("calls", 0), ctb.get("rows", 0),
               ctb.get("serve_requests", 0), ctb.get("fallbacks", 0)))
        for bucket, h in sorted((ctb.get("latency_s") or {}).items(),
                                key=lambda kv: int(kv[0])):
            if h.get("count"):
                row("    bucket %s" % bucket, "n=%d p50=%.6g p99=%.6g"
                    % (h["count"], h.get("p50", float("nan")),
                       h.get("p99", float("nan"))))
    ing = summary.get("ingest") or {}
    if ing:
        lines.append("  ingest:")
        rps = ing.get("rows_per_s") or {}
        row("    chunks/rows", "%d/%d"
            % (ing.get("chunks", 0), ing.get("rows", 0)))
        if rps.get("count"):
            row("    chunk rows/s", "p50=%.6g p99=%.6g"
                % (rps.get("p50", float("nan")),
                   rps.get("p99", float("nan"))))
        row("    pipeline stall_ms",
            "-" if ing.get("stall_ms") is None
            else "%.3f" % ing["stall_ms"])
        hw = ing.get("rss_high_water_bytes")
        row("    host rss high-water",
            "-" if hw is None else "%.1f MiB" % (hw / (1 << 20)))
    qnt = summary.get("quant") or {}
    if qnt:
        lines.append("  quant:")
        row("    chunks/iterations", "%d/%d"
            % (qnt.get("chunks", 0), qnt.get("iterations", 0)))
        row("    levels (grad/hess)", "%s/%s"
            % (num(qnt.get("grad_levels"), "%d")
               if qnt.get("grad_levels") is not None else "-",
               num(qnt.get("hess_levels"), "%d")
               if qnt.get("hess_levels") is not None else "-"))
        row("    hist operand channels",
            "-" if qnt.get("hist_channels") is None
            else "%d" % qnt["hist_channels"])
    plan = summary.get("plan") or {}
    if plan:
        row("plan provenance", "%s (cache=%s, fallbacks=%d)"
            % (plan.get("provenance", "analytic"),
               plan.get("cache_path") or "-",
               plan.get("cache_fallbacks", 0)))
        for site, info in sorted((plan.get("sites") or {}).items()):
            row("  plan[%s]" % site, "%s %s"
                % (info.get("provenance"), info.get("key") or ""))
    comp = summary.get("compile") or {}
    if comp.get("keys"):
        lines.append("  compile:")
        row("    compile_seconds_total",
            "%.6g (compiles %d, warm loads %d%s)"
            % (comp.get("compile_seconds_total", 0.0),
               comp.get("compiles", 0), comp.get("warm_loads", 0),
               (", unresolved %d" % comp["unresolved"])
               if comp.get("unresolved") else ""))
        for key, info in sorted(comp["keys"].items()):
            steady = info.get("steady_p50_s")
            row("    %s" % key,
                "n=%d warm=%d compile_s=%.6g steady_p50=%s"
                % (info.get("compiles", 0), info.get("warm_loads", 0),
                   info.get("compile_s", 0.0),
                   "-" if steady is None else "%.6g" % steady))
    dm = summary.get("devmem") or {}
    if dm.get("devices"):
        lines.append("  devmem:")
        row("    peak_bytes_max", "%d" % dm.get("peak_bytes_max", 0))
        for dev, ms in sorted(dm["devices"].items()):
            row("    device %s" % dev,
                " ".join("%s=%d" % (k, v) for k, v in sorted(ms.items())))
    al = summary.get("alerts") or {}
    if al:
        lines.append("  alerts:")
        row("    fired_total", "%d%s"
            % (al.get("fired_total", 0),
               "" if al.get("enabled", True) else " (no engine: "
               "out-of-band incidents only)"))
        for st in al.get("series") or []:
            if st.get("state") == "firing" or st.get("fired"):
                row("    %s[%s]" % (st.get("rule"), st.get("series", "-")),
                    "%s value=%s fast=%s slow=%s"
                    % (st.get("state", "?"), st.get("value", "-"),
                       st.get("fast_burn", "-"), st.get("slow_burn", "-")))
        for name, n in sorted((al.get("external") or {}).items()):
            row("    external %s" % name, "%d" % n)
    prof = summary.get("profiling") or {}
    if prof.get("captures"):
        lines.append("  profiler captures:")
        for c in prof["captures"]:
            row("    #%d %s" % (c.get("n", 0), c.get("reason", "?")),
                c.get("error") or c.get("dir", "-"))
    res = summary.get("resilience") or {}
    shown = {k: v for k, v in sorted(res.items())
             if (isinstance(v, (int, float)) and v)
             or (isinstance(v, dict) and v.get("count"))}
    if shown:
        lines.append("  resilience:")
        for k, v in shown.items():
            if isinstance(v, dict):
                row("    " + k, "n=%d p50=%.6g p99=%.6g"
                    % (v["count"], v.get("p50", float("nan")),
                       v.get("p99", float("nan"))))
            else:
                row("    " + k, num(v))
    for name, h in sorted((summary.get("histograms") or {}).items()):
        if h.get("count"):
            row(name, "n=%d p50=%.6g p99=%.6g sum=%.6g"
                % (h["count"], h.get("p50", float("nan")),
                   h.get("p99", float("nan")), h.get("sum", 0.0)))
    phases = summary.get("host_phases") or {}
    if phases:
        lines.append("  host phases:")
        for name, tot in sorted(phases.items(), key=lambda kv: -kv[1]):
            row("    " + name, "%.6f s" % tot)
    counters = summary.get("counters") or {}
    for name, v in sorted(counters.items()):
        row("counter " + name, "%d" % v)
    return "\n".join(lines)


def _feature_importance_block(gbdt, top_n: int = 50):
    """{"split": {name: n}, "gain": {name: x}} for the trained model's
    nonzero-importance features (top ``top_n`` by gain); None for models
    with no trees or no importance surface."""
    try:
        split = gbdt.feature_importance("split")
        gain = gbdt.feature_importance("gain")
    except Exception:
        return None
    names = list(getattr(gbdt, "feature_names", []) or [])

    def nm(i):
        return names[i] if i < len(names) else "Column_%d" % i

    order = sorted(range(len(gain)), key=lambda i: (-gain[i], i))
    order = [i for i in order if split[i] > 0 or gain[i] > 0][:top_n]
    if not order:
        return None
    return {"split": {nm(i): int(split[i]) for i in order},
            "gain": {nm(i): round(float(gain[i]), 6) for i in order}}


def finalize_run(tele: Telemetry, gbdt=None, wall_s: Optional[float] = None,
                 iters: Optional[int] = None,
                 extra: Optional[Dict[str, Any]] = None,
                 summary_path: Optional[str] = None) -> Dict[str, Any]:
    """Close out a telemetry run: record headline gauges, the MFU estimate
    (when a booster is at hand), write ``<out>.summary.json`` next to the
    JSONL, emit a ``run_end`` event, and return the summary dict.

    Gauges the training driver already recorded WIN: ``GBDT.train`` times
    the train loop only, while a CLI caller's ``wall_s`` spans dataset
    loading and compile too — overwriting would make the same training
    produce different row-trees/s headlines per entry point.  The
    ``wall_s``/``iters`` arguments are the fallback for runs that never
    went through a recording driver (bench's timed window)."""
    from ..utils.log import Log
    if wall_s is not None and tele.gauge("train_wall_s").value is None:
        tele.gauge("train_wall_s").set(wall_s)
    if iters is not None and tele.gauge("train_iterations").value is None:
        tele.gauge("train_iterations").set(iters)
    eff_wall = tele.gauge("train_wall_s").value
    eff_iters = tele.gauge("train_iterations").value
    if gbdt is not None:
        if tele.gauge("train_rows").value is None:
            tele.gauge("train_rows").set(int(gbdt.num_data))
        if eff_wall:
            from .mfu import record_training_estimate
            record_training_estimate(
                tele, gbdt, eff_wall,
                iters=int(eff_iters) if eff_iters else None)
        # split/gain feature importance rides the summary: the quality
        # table ranks drifted features by importance x PSI, and the
        # artifact should carry the ranking weights it used (top 50 by
        # gain to bound artifact size)
        fi = _feature_importance_block(gbdt)
        if fi is not None:
            extra = dict(extra or {})
            extra.setdefault("feature_importance", fi)
    # one final devmem poll so the summary's high-water covers the whole
    # run even when no exporter ever scraped (quietly empty on CPU)
    from . import devmem as _devmem
    _devmem.sample(tele, phase="finalize")
    summary = summarize(tele, extra=extra)
    tele.event("run_end", wall_s=wall_s, iterations=iters)
    path = summary_path
    if path is None and tele.out_path:
        # the summary is named from the UNsharded base so the leader's
        # <out>.summary.json sits next to every rank's shard
        path = (getattr(tele, "summary_base", None)
                or tele.out_path) + ".summary.json"
    if path and getattr(tele, "rank", None) not in (None, 0):
        # leader-only file discipline: non-leader ranks keep their shard
        # JSONL but must not race d hosts over one summary path
        path = None
    if path:
        from ..utils.file_io import atomic_write
        atomic_write(path, json.dumps(summary, indent=1, default=str))
        Log.info("Wrote telemetry summary %s", path)
    tele.flush()
    Log.debug("%s", human_table(summary))
    return summary
