# Training callbacks (role of the reference R-package/R/callback.R):
# closures invoked once per iteration with the shared training env
# (env$iter, env$evals named per valid set, env$booster, env$stop).

#' Record per-iteration evaluation results into env$record
#' @export
cb_record_evaluation <- function() {
  function(env) {
    if (is.null(env$record)) env$record <- list()
    for (nm in names(env$evals)) {
      env$record[[nm]] <- c(env$record[[nm]], list(env$evals[[nm]]))
    }
  }
}

#' Print evaluation results every `period` iterations
#' @export
cb_print_evaluation <- function(period = 1L) {
  function(env) {
    if (env$iter %% period != 0L) return(invisible())
    for (nm in names(env$evals)) {
      vals <- paste(sprintf("%.6f", env$evals[[nm]]), collapse = ", ")
      message(sprintf("[%d] %s: %s", env$iter, nm, vals))
    }
  }
}

#' Early stopping: stop when the FIRST metric of the FIRST valid set has not
#' improved for `rounds` iterations (lower is better unless the booster's
#' params name a higher-better metric such as auc/ndcg/map)
#' @export
cb_early_stop <- function(rounds) {
  best <- NULL
  best_iter <- 0L
  function(env) {
    if (length(env$evals) == 0L) return(invisible())
    v <- env$evals[[1L]][1L]
    metrics <- tolower(unlist(strsplit(
      paste(env$booster$params$metric, collapse = ","), ",")))
    higher <- any(grepl("^auc", metrics[1]) | grepl("^ndcg", metrics[1])
                  | metrics[1] == "map" | grepl("^map@", metrics[1]))
    improved <- is.null(best) || (if (higher) v > best else v < best)
    if (improved) {
      best <<- v
      best_iter <<- env$iter
      env$booster$best_iter <- env$iter
    } else if (env$iter - best_iter >= rounds) {
      message(sprintf("Early stopping at iteration %d (best %d)",
                      env$iter, best_iter))
      env$stop <- TRUE
    }
  }
}
