"""Wide-F histogram benchmark: compile time + measured ns/row at Bosch
shape (F=968), factored vs classic layouts.

Round 5 could only offer a DERIVED ~2.5x factored-vs-classic claim at this
width because both unrolled kernel layouts hit multi-10-minute XLA/Mosaic
compiles; the round-6 grid-over-groups layout is the fix, and this tool
turns the claim into a measured number (PERF.md "Wide-F").

Per configuration it reports:
- compile_s: wall-clock of the first (compiling) call
- ns_row: device time per (row) from the xplane trace of warm calls
- ns_row_feature: the same per (row, feature) — the cross-width comparable

Configs: F=968 at B=64 (factored; the 63-bin Bosch setting) and the same
shape FORCED onto the classic packed-tile path, plus F=968 at B=256 where
the 4 MiB accumulator gate makes classic the only path.

Usage: python tools/bench_widef.py [--rows 262144] [--json]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=262_144)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import lightgbm_tpu.core.histogram as H
    from tools.profile_tree import aggregate_xplane

    F = 968
    n = args.rows
    rng = np.random.RandomState(0)
    results = {}

    def measure(tag, b, force_classic):
        voff = -(-F // 4) * 4
        W = -(-(voff + 8) // 128) * 128
        rows = np.zeros((n, W), np.uint8)
        rows[:, :F] = rng.randint(0, b, size=(n, F))
        rows[:, voff:voff + 8] = rng.randint(0, 255, size=(n, 8))
        r = jnp.asarray(rows)
        orig = H._use_factored
        if force_classic:
            H._use_factored = lambda f, bb: False
        H.histogram_pallas_rows.clear_cache()
        try:
            t0 = time.perf_counter()
            out = H.histogram_pallas_rows(
                r, b, jnp.int32(0), jnp.int32(n), num_features=F, voff=voff,
                row_tile=2048)
            jax.block_until_ready(out)
            compile_s = time.perf_counter() - t0
            reps = 3
            trace_dir = "/tmp/lgbm_tpu_widef/" + tag
            with jax.profiler.trace(trace_dir):
                for _ in range(reps):
                    out = H.histogram_pallas_rows(
                        r, b, jnp.int32(0), jnp.int32(n), num_features=F,
                        voff=voff, row_tile=2048)
                    jax.block_until_ready(out)
                float(jax.device_get(out[0, 0, 0]))
            ms = max(aggregate_xplane(trace_dir, top=40),
                     key=lambda q: q[1])[1] / reps
        finally:
            H._use_factored = orig
            H.histogram_pallas_rows.clear_cache()
        results[tag] = {
            "compile_s": round(compile_s, 1),
            "ns_row": round(ms * 1e6 / n, 3),
            "ns_row_feature": round(ms * 1e6 / (n * F), 5),
        }
        if not args.json:
            print("%-28s compile %6.1f s   %8.2f ns/row   %.4f ns/(row*feat)"
                  % (tag, compile_s, results[tag]["ns_row"],
                     results[tag]["ns_row_feature"]), flush=True)

    if not args.json:
        print("wide-F histogram (F=%d, %d rows, grid-over-groups layout)"
              % (F, n), flush=True)
    measure("F968_B64_factored", 64, force_classic=False)
    measure("F968_B64_classic", 64, force_classic=True)
    measure("F968_B256_classic", 256, force_classic=False)  # gate -> classic
    if args.json:
        print(json.dumps(results))


if __name__ == "__main__":
    main()
