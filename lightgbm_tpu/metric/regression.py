"""Pointwise regression metrics (src/metric/regression_metric.hpp)."""
from __future__ import annotations

import numpy as np

from .metric import Metric


class _RegressionMetric(Metric):
    metric_name = ""
    use_objective_convert = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.names = [self.metric_name]

    def point_loss(self, label, score):
        raise NotImplementedError

    def average(self, sum_loss, sum_weights):
        return sum_loss / sum_weights

    def eval(self, score, objective=None):
        s = np.asarray(score, dtype=np.float64).reshape(-1)
        if objective is not None and self.use_objective_convert:
            s = np.asarray(objective.convert_output(s))
        pointwise = self.point_loss(self.label, s)
        if self.weights is not None:
            total = float((pointwise * self.weights).sum())
        else:
            total = float(pointwise.sum())
        return [self.average(total, self.sum_weights)]


class L2Metric(_RegressionMetric):
    metric_name = "l2"

    def point_loss(self, y, s):
        return (s - y) ** 2


class RMSEMetric(L2Metric):
    metric_name = "rmse"

    def average(self, sum_loss, sum_weights):
        return float(np.sqrt(sum_loss / sum_weights))


class L1Metric(_RegressionMetric):
    metric_name = "l1"

    def point_loss(self, y, s):
        return np.abs(s - y)


class QuantileMetric(_RegressionMetric):
    metric_name = "quantile"

    def point_loss(self, y, s):
        delta = y - s
        a = self.config.alpha
        return np.where(delta < 0, (a - 1.0) * delta, a * delta)


class HuberLossMetric(_RegressionMetric):
    metric_name = "huber"

    def point_loss(self, y, s):
        diff = s - y
        a = self.config.alpha
        return np.where(np.abs(diff) <= a, 0.5 * diff * diff,
                        a * (np.abs(diff) - 0.5 * a))


class FairLossMetric(_RegressionMetric):
    metric_name = "fair"

    def point_loss(self, y, s):
        x = np.abs(s - y)
        c = self.config.fair_c
        return c * x - c * c * np.log(1.0 + x / c)


class PoissonMetric(_RegressionMetric):
    metric_name = "poisson"

    def point_loss(self, y, s):
        s = np.maximum(s, 1e-10)
        return s - y * np.log(s)


class MAPEMetric(_RegressionMetric):
    metric_name = "mape"

    def point_loss(self, y, s):
        return np.abs(y - s) / np.maximum(1.0, np.abs(y))


class GammaMetric(_RegressionMetric):
    metric_name = "gamma"

    def point_loss(self, y, s):
        # negative gamma log-likelihood with psi=1 (regression_metric.hpp:261-268)
        safe = np.maximum(s, 1e-20)
        theta = -1.0 / safe
        b = -np.log(np.maximum(-theta, 1e-20))
        ysafe = np.maximum(y, 1e-20)
        c = np.log(ysafe) - np.log(ysafe)
        return -((y * theta - b) + c)


class GammaDevianceMetric(_RegressionMetric):
    metric_name = "gamma_deviance"

    def point_loss(self, y, s):
        tmp = y / (s + 1e-9)
        return tmp - np.log(np.maximum(tmp, 1e-20)) - 1

    def average(self, sum_loss, sum_weights):
        return sum_loss * 2


class TweedieMetric(_RegressionMetric):
    metric_name = "tweedie"

    def point_loss(self, y, s):
        rho = self.config.tweedie_variance_power
        s = np.maximum(s, 1e-10)
        a = y * np.exp((1 - rho) * np.log(s)) / (1 - rho)
        b = np.exp((2 - rho) * np.log(s)) / (2 - rho)
        return -a + b
