"""The three formerly-silent no-op params now wired to behavior (round 6):

- pos/neg_bagging_fraction balanced bagging (config.h:261-281)
- extra_trees randomized thresholds (config.h:318)
- feature_contri per-feature gain scaling (config.h:432-436)
"""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config
from lightgbm_tpu.core.split import (FeatureInfo, SplitParams,
                                     per_feature_best)
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.objective import create_objective


def _binary_problem(n=4000, f=6, seed=0, pos_rate=0.5):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    logit = X[:, 0] * 2.0 + 0.5 * X[:, 1] + rng.normal(scale=0.3, size=n)
    thr = np.quantile(logit, 1.0 - pos_rate)
    y = (logit > thr).astype(np.float64)
    return X, y


def _booster(X, y, **cfg_kwargs):
    ds = BinnedDataset.from_matrix(X, label=y, max_bin=63)
    cfg = Config(objective="binary", num_leaves=15, num_iterations=4,
                 learning_rate=0.2, max_bin=63, verbosity=-1, **cfg_kwargs)
    return GBDT(cfg, ds, create_objective("binary", cfg))


# ---- balanced bagging ----

def test_neg_bagging_fraction_downsamples_only_negatives():
    X, y = _binary_problem(pos_rate=0.3)
    b = _booster(X, y, neg_bagging_fraction=0.3, bagging_freq=1,
                 bagging_seed=7)
    b._bagging(0)
    mask = np.asarray(b.bag_mask)[:b.num_data]
    pos_kept = mask[y > 0].mean()
    neg_kept = mask[y <= 0].mean()
    assert pos_kept == 1.0, "pos_bagging_fraction=1.0 must keep every positive"
    assert 0.2 < neg_kept < 0.4, f"negatives kept at {neg_kept}, want ~0.3"
    assert b.bag_data_cnt == int(mask.sum())


def test_balanced_bagging_is_deterministic_and_windowed():
    X, y = _binary_problem(pos_rate=0.4)
    b1 = _booster(X, y, pos_bagging_fraction=0.6, neg_bagging_fraction=0.2,
                  bagging_freq=2, bagging_seed=11)
    b2 = _booster(X, y, pos_bagging_fraction=0.6, neg_bagging_fraction=0.2,
                  bagging_freq=2, bagging_seed=11)
    b1._bagging(0)
    b2._bagging(0)
    np.testing.assert_array_equal(np.asarray(b1.bag_mask),
                                  np.asarray(b2.bag_mask))
    m0 = np.asarray(b1.bag_mask).copy()
    b1._bagging(1)   # same freq window -> mask unchanged (freq=2)
    np.testing.assert_array_equal(np.asarray(b1.bag_mask), m0)
    b1._bagging(2)   # new window -> new draw
    assert not np.array_equal(np.asarray(b1.bag_mask), m0)


def test_balanced_bagging_trains_and_disables_fusion():
    X, y = _binary_problem()
    b = _booster(X, y, pos_bagging_fraction=0.9, neg_bagging_fraction=0.5,
                 bagging_freq=1)
    assert not b._can_fuse_iters(), \
        "per-class fractions need labels, which the fused scan cannot see"
    for _ in range(3):
        b.train_one_iter()
    assert b.num_trees == 3
    # active bagging must actually shrink the bag
    assert b.bag_data_cnt < b.num_data


# ---- extra_trees ----

def _toy_feature_best(params, f=12, b=32, seed=0):
    rng = np.random.RandomState(seed)
    hist = jnp.asarray(np.abs(rng.normal(size=(f, 2, b))).astype(np.float32))
    feat = FeatureInfo(
        num_bin=jnp.full((f,), b, jnp.int32),
        missing_type=jnp.zeros((f,), jnp.int32),
        default_bin=jnp.zeros((f,), jnp.int32),
        is_categorical=jnp.zeros((f,), bool),
        monotone=jnp.zeros((f,), jnp.int32))
    mask = jnp.ones((f,), bool)
    sg = jnp.float32(float(hist[:, 0, :].sum() / f))
    sh = jnp.float32(float(hist[:, 1, :].sum() / f))
    return per_feature_best(hist, feat, mask, sg, sh, jnp.int32(5000),
                            params)


def test_extra_trees_single_random_threshold_per_feature():
    base = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=1e-3)
    et = base._replace(extra_trees=True, extra_seed=4)
    fb_full = _toy_feature_best(base)
    fb_et1 = _toy_feature_best(et)
    fb_et2 = _toy_feature_best(et)
    # deterministic given the seed
    np.testing.assert_array_equal(np.asarray(fb_et1.threshold),
                                  np.asarray(fb_et2.threshold))
    # the randomized scan must actually restrict candidates: across 12
    # features, at least one random threshold differs from the full scan's
    # argmax, and no gain may EXCEED the full scan's (subset of candidates)
    assert (np.asarray(fb_et1.threshold)
            != np.asarray(fb_full.threshold)).any()
    g_et = np.asarray(fb_et1.gain)
    g_full = np.asarray(fb_full.gain)
    found = g_et > -np.inf
    assert (g_et[found] <= g_full[found] + 1e-4).all()
    # a different extra_seed re-draws
    fb_et3 = _toy_feature_best(et._replace(extra_seed=99))
    assert (np.asarray(fb_et3.threshold)
            != np.asarray(fb_et1.threshold)).any()


def test_extra_trees_end_to_end_changes_model_and_trains():
    X, y = _binary_problem()
    b_def = _booster(X, y)
    b_et = _booster(X, y, extra_trees=True)
    for _ in range(3):
        b_def.train_one_iter()
        b_et.train_one_iter()
    t_def = b_def.models[0]
    t_et = b_et.models[0]
    same = (t_def.num_leaves == t_et.num_leaves
            and np.array_equal(t_def.threshold[:t_def.num_leaves - 1],
                               t_et.threshold[:t_et.num_leaves - 1]))
    assert not same, "extra_trees must randomize the chosen thresholds"
    pred = np.asarray(b_et.predict(X, raw_score=True))
    from lightgbm_tpu.metric.binary import weighted_auc
    assert weighted_auc(y, pred, None) > 0.8, "extra_trees model must learn"


# ---- feature_contri ----

def test_feature_contri_zero_vetoes_dominant_feature():
    X, y = _binary_problem()
    b_def = _booster(X, y)
    b_def.train_one_iter()
    root_def = int(b_def.models[0].split_feature[0])
    assert root_def == 0, "feature 0 carries the signal in this problem"
    contri = [1.0] * X.shape[1]
    contri[0] = 0.0
    b_pen = _booster(X, y, feature_contri=contri)
    b_pen.train_one_iter()
    tree = b_pen.models[0]
    used = set(int(v) for v in tree.split_feature[:tree.num_leaves - 1])
    assert 0 not in used, \
        "feature_contri[0]=0 must zero feature 0's gain everywhere"


def test_feature_contri_scales_reported_gain():
    X, y = _binary_problem()
    b_half = _booster(X, y, feature_contri=[0.5] * X.shape[1])
    b_def = _booster(X, y)
    b_half.train_one_iter()
    b_def.train_one_iter()
    t_h, t_d = b_half.models[0], b_def.models[0]
    # identical structure (uniform scaling preserves the argmax)...
    np.testing.assert_array_equal(t_h.split_feature[:t_h.num_leaves - 1],
                                  t_d.split_feature[:t_d.num_leaves - 1])
    # ...but the recorded split gains are halved (config.h:432 semantics)
    np.testing.assert_allclose(
        np.asarray(t_h.split_gain[:t_h.num_leaves - 1]),
        0.5 * np.asarray(t_d.split_gain[:t_d.num_leaves - 1]), rtol=1e-5)
