"""Round 20: error-budgeted mixed-precision serving + ensemble compaction.

The bf16 tier's contract is pinned from both ends: the exact path stays
BYTE-identical (every dtype cast in ``scan_blocks`` is a no-op for f32 —
the jaxpr may not change), while the lossy tier keeps leaf *routing*
bit-exact (integer/threshold decide + a ±1 path-sign dot that bf16
represents exactly) and only the weighted leaf sum carries rounding, so
the measured score delta stays under the declared ``bf16_max_score_delta``
budget.  Serving-side: exact and bf16 requests NEVER share a dispatch
(the batch key carries the tier), contrib has no lossy tier anywhere on
the ladder, a quantize-only compacted republish is a pure jit-cache hit,
and the quality plane folds both tiers' scores through the same training
fingerprint (no per-tier baselines, no per-tier false alarms).  The perf
gate is pinned operational: a doctored over-budget artifact must FAIL.
"""
import json
import os
import sys

import numpy as np
import pytest

from lightgbm_tpu import obs
from lightgbm_tpu.boosting.gbdt import GBDT
from lightgbm_tpu.config import Config
from lightgbm_tpu.core.compact import (compact_booster, compact_trees,
                                       measure_compaction)
from lightgbm_tpu.core.predict_fused import FusedPredictor
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.obs import recompile
from lightgbm_tpu.objective import create_objective
from lightgbm_tpu.serving import Server
from lightgbm_tpu.utils.log import LightGBMError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_off():
    obs.disable()
    yield
    obs.disable()


def _train(seed=0, n=800, objective="regression", num_leaves=8, iters=10,
           features=6, **extra):
    rng = np.random.RandomState(seed)
    X = rng.uniform(-2, 2, size=(n, features)).astype(np.float32)
    base = X[:, 0] * 2 + np.sin(X[:, 1] * 2)
    if objective == "binary":
        y = (base + rng.normal(scale=0.4, size=n) > 0).astype(np.float64)
    else:
        y = (base + 0.1 * rng.normal(size=n)).astype(np.float64)
    cfg = Config(objective=objective, num_leaves=num_leaves,
                 min_data_in_leaf=5, verbosity=-1, num_iterations=iters,
                 **extra)
    ds = BinnedDataset.from_matrix(X, label=y, max_bin=cfg.max_bin,
                                   min_data_in_leaf=cfg.min_data_in_leaf)
    b = GBDT(cfg, ds, create_objective(cfg.objective, cfg))
    for _ in range(iters):
        b.train_one_iter()
    return b, X


@pytest.fixture(scope="module")
def model():
    return _train(seed=0, iters=12, num_leaves=15)


# ---- exact path byte-identity (the non-negotiable) ----

def test_exact_path_byte_identical_and_bf16_free(model):
    """precision='exact' is the SAME program as before the tier existed:
    outputs byte-identical to the default predictor and the traced jaxpr
    carries no bfloat16 anywhere (every cast is a no-op for f32)."""
    import jax
    from lightgbm_tpu.core.predict_fused import predict_blocked
    b, X = model
    fp_default = FusedPredictor(b.models)
    fp_exact = FusedPredictor(b.models, precision="exact")
    got_d = np.asarray(fp_default(X[:200]))
    got_e = np.asarray(fp_exact(X[:200]))
    np.testing.assert_array_equal(got_d, got_e)
    assert got_e.dtype == got_d.dtype
    jx = str(jax.make_jaxpr(predict_blocked)(fp_exact.ens,
                                             np.asarray(X[:64])))
    assert "bf16" not in jx and "bfloat16" not in jx
    # the booster API default is likewise the exact tier, bit for bit
    np.testing.assert_array_equal(
        b.predict(X[:200], raw_score=True),
        b.predict(X[:200], raw_score=True, precision="exact"))


def test_bf16_deterministic_bounded_and_distinct(model):
    """The lossy tier is deterministic (lossy, not noisy), measurably
    different from exact (the knob does something), and within the
    declared budget — routing exactness keeps the error at leaf-rounding
    scale, not misroute scale."""
    b, X = model
    with open(os.path.join(REPO, "PERF_BUDGETS.json")) as fh:
        budget = float(json.load(fh)["budgets"]["bf16_max_score_delta"])
    exact = b.predict(X[:400], raw_score=True)
    bf16_a = b.predict(X[:400], raw_score=True, precision="bf16")
    bf16_b = b.predict(X[:400], raw_score=True, precision="bf16")
    np.testing.assert_array_equal(bf16_a, bf16_b)
    delta = float(np.max(np.abs(exact - bf16_a)))
    assert 0.0 < delta <= budget
    # leaf routing is tier-independent: bf16 path signs are ±1/0 exactly,
    # so the leaf-index surface (pure routing) cannot move
    np.testing.assert_array_equal(b.predict_leaf_index(X[:200], -1),
                                  b.predict_leaf_index(X[:200], -1))


def test_bf16_ensemble_halves_leaf_bytes(model):
    """The mechanism the tier buys: the [G,M,L] routing/leaf operands are
    2-byte, halving the bytes every row-tree streams per dispatch."""
    b, _ = model
    fp = FusedPredictor(b.models)
    fpb = FusedPredictor(b.models, precision="bf16")
    assert fpb.ens.path_sign.dtype == "bfloat16"
    assert fpb.ens.leaf_value.dtype == "bfloat16"
    assert (fpb.ens.path_sign.nbytes + fpb.ens.leaf_value.nbytes) * 2 \
        == fp.ens.path_sign.nbytes + fp.ens.leaf_value.nbytes


# ---- validation + contrib rejection (no silent upgrades) ----

def test_precision_validation_and_contrib_rejection(model):
    from lightgbm_tpu.basic import Booster
    b, X = model
    with pytest.raises(ValueError):
        b.predict(X[:8], precision="fp8")
    with pytest.raises(ValueError):
        FusedPredictor(b.models, precision="f16")
    fpb = FusedPredictor(b.models, precision="bf16")
    with pytest.raises(ValueError):
        fpb.predict_contrib(X[:8], b.max_feature_idx + 2)
    bw = Booster(model_str=b.save_model_to_string())
    with pytest.raises(LightGBMError):
        bw.predict(X[:8], pred_contrib=True, precision="bf16")
    with Server(max_batch_wait_us=0) as srv:
        srv.register("m", b)
        with pytest.raises(LightGBMError):
            srv.submit("m", X[:8], pred_contrib=True, precision="bf16")
        with pytest.raises(LightGBMError):
            srv.submit("m", X[:8], precision="int8")


# ---- batch-key isolation: tiers never coalesce ----

def test_exact_and_bf16_never_share_a_dispatch(model):
    """Concurrent exact + bf16 requests for the same rows coalesce into
    per-tier batches only: every serve_batch event carries one tier, the
    per-tier request counters add up, and each response is bit-exact
    against ITS tier's fused program — a cross-tier ride would show up as
    the wrong scores."""
    b, X = model
    ref_e = np.asarray(FusedPredictor(b.models)(X[:64]))
    ref_b = np.asarray(FusedPredictor(b.models, precision="bf16")(X[:64]))
    assert not np.array_equal(ref_e, ref_b), \
        "premise: the tiers must disagree for isolation to be observable"
    tele = obs.configure(freq=1, entry="test_precision")
    with Server(max_batch_wait_us=30000) as srv:
        srv.register("m", b)
        srv.registry._resident["m"].warm((128,),
                                         precisions=("exact", "bf16"))
        futs = [srv.submit("m", X[:64], raw_score=True,
                           precision=("bf16" if i % 2 else "exact"))
                for i in range(6)]
        outs = [np.asarray(f.result(timeout=60)) for f in futs]
    for i, got in enumerate(outs):
        np.testing.assert_array_equal(got, ref_b if i % 2 else ref_e)
    ev = [e for e in tele.events if e["kind"] == "serve_batch"]
    assert {e["precision"] for e in ev} == {"exact", "bf16"}
    by_tier = {"exact": 0, "bf16": 0}
    for e in ev:
        by_tier[e["precision"]] += e["requests"]
    assert by_tier == {"exact": 3, "bf16": 3}
    assert tele.counter("serve_requests_precision_exact").value == 3
    assert tele.counter("serve_requests_precision_bf16").value == 3
    # and the 30ms coalescing window DID merge within each tier: fewer
    # batches than requests proves the keys only split across tiers
    assert len(ev) < 6


# ---- compaction ----

def test_compact_quantize_only_preserves_structure(model):
    """Codebook quantization alone (no merge/prune/cap) keeps every
    tree's structure: same leaf counts, same splits, leaf values on the
    codebook grid, declared bound respected on real rows."""
    b, X = model
    trees = b.models
    out, stats = compact_trees(trees, leaf_codes=255, merge_subtrees=False)
    assert [t.num_leaves for t in out] == [t.num_leaves for t in trees]
    for told, tnew in zip(trees, out):
        # _rebuild renumbers nodes pre-order, so compare the split
        # multiset, not positional arrays
        old_splits = sorted(zip(np.asarray(told.split_feature).tolist(),
                                np.asarray(told.threshold).tolist()))
        new_splits = sorted(zip(np.asarray(tnew.split_feature).tolist(),
                                np.asarray(tnew.threshold).tolist()))
        assert old_splits == new_splits
    fp_old = FusedPredictor(trees)
    fp_new = FusedPredictor(out)
    delta = float(np.max(np.abs(np.asarray(fp_old(X[:400]))
                                - np.asarray(fp_new(X[:400])))))
    assert delta <= stats["declared_max_score_delta"]
    assert stats["tree_reduction"] == 0.0


def test_compact_booster_reduces_and_stays_in_budget(model):
    """The full pipeline (prune + cap + quantize + merge) on the bench
    recipe: real node/byte reduction, measured delta within the declared
    bound, AUC preserved on the training rows, and the distilled
    generation round-trips through model text exactly."""
    b, X = _train(seed=3, objective="binary", iters=30, num_leaves=31,
                  n=2000, features=10)
    gen, stats = compact_booster(b, leaf_codes=255, prune_frac=0.05,
                                 leaf_cap=24)
    assert stats["tree_reduction"] > 0.0
    assert stats["byte_reduction"] > 0.0
    assert stats["max_leaves_out"] <= 24 < stats["max_leaves_in"]
    y = (np.asarray(b.predict(X, raw_score=True)) > 0).astype(np.float64)
    meas = measure_compaction(b, gen, X[:1000], y=y[:1000])
    assert meas["max_score_delta"] <= stats["declared_max_score_delta"]
    with open(os.path.join(REPO, "PERF_BUDGETS.json")) as fh:
        budgets = json.load(fh)["budgets"]
    assert meas["auc_delta"] <= budgets["compact_auc_delta_max"]
    # immutable-generation discipline: text round-trip is exact
    gen2 = GBDT(gen.config)
    gen2.load_model_from_string(gen.save_model_to_string())
    np.testing.assert_array_equal(gen.predict(X[:200], raw_score=True),
                                  gen2.predict(X[:200], raw_score=True))


def test_compacted_republish_is_pure_jit_cache_hit(model):
    """A quantize-only compacted generation stacks to the SAME shapes as
    its parent, so the registry hot-swap republish is a pure jit-cache
    hit: recompile gauge flat across swap + post-swap traffic, responses
    bit-exact vs the compacted program, fingerprints carried."""
    b, X = model
    gen, _ = compact_booster(b, leaf_codes=255, merge_subtrees=False)
    ref = np.asarray(FusedPredictor(gen.models)(X[:64]))
    with Server(max_batch_wait_us=0) as srv:
        srv.register("m", b)
        srv.predict("m", X[:64], raw_score=True)  # warm the rung
        base = recompile.total()
        srv.swap("m", gen, warm=False)
        got = srv.predict("m", X[:64], raw_score=True)
        np.testing.assert_array_equal(got, ref)
        assert recompile.total() - base == 0, \
            "same-shape compacted republish must not compile anything"
        stats = srv.stats()
        assert stats["dropped"] == 0 and stats["failed"] == 0
    assert getattr(gen, "_score_fingerprint_raw", None) \
        is getattr(b, "_score_fingerprint_raw", None)


# ---- quality plane: one fingerprint path for both tiers ----

def test_quality_plane_no_per_tier_false_alarm(model):
    """bf16 scores fold into score-PSI through the SAME training
    fingerprint as exact: one model entry (no per-tier baselines), and
    serving the same rows on both tiers stays at level ok — the bf16
    rounding is orders of magnitude below a decile width."""
    from lightgbm_tpu.obs.quality import capture_fingerprints
    b, X = _train(seed=5, iters=8)
    capture_fingerprints(b)
    assert getattr(b, "_score_fingerprint_raw", None) is not None
    tele = obs.configure(freq=1, entry="test_precision_quality")
    rng = np.random.RandomState(11)
    with Server(max_batch_wait_us=0) as srv:
        srv.register("m", b)
        srv.registry._resident["m"].warm((128, 1024),
                                         precisions=("exact", "bf16"))
        for i in range(12):
            rows = X[rng.randint(0, len(X), 256)]
            srv.submit("m", rows, raw_score=True,
                       precision=("bf16" if i % 2 else "exact")
                       ).result(timeout=60)
    mon = tele.quality
    assert mon is not None
    snap = mon.snapshot()
    assert set(snap["models"]) == {"m"}, \
        "tiers must not mint separate quality entries"
    info = snap["models"]["m"]
    assert info["score_psi"] is not None
    assert info["level"] == "ok", \
        "mixed-tier traffic on in-distribution rows must not alarm"


# ---- the gate is operational: doctored artifacts FAIL ----

def test_perf_gate_fails_doctored_over_budget_artifact(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import perf_gate
    finally:
        sys.path.pop(0)
    src = os.path.join(REPO, "BENCH_precision_interp.json")
    with open(src) as fh:
        doc = json.load(fh)
    budgets = os.path.join(REPO, "PERF_BUDGETS.json")
    # the committed artifact passes as-is
    assert perf_gate.run_gate([src], budgets) == 0
    with open(budgets) as fh:
        bspec = json.load(fh)["budgets"]
    # doctor 1: bf16 delta over budget
    bad = json.loads(json.dumps(doc))
    bad["precision"]["bf16"]["max_score_delta"] = \
        bspec["bf16_max_score_delta"] * 2.0
    p1 = str(tmp_path / "over_delta.json")
    with open(p1, "w") as fh:
        json.dump(bad, fh)
    assert perf_gate.run_gate([p1], budgets) == 1
    # doctor 2: compaction AUC over budget
    bad = json.loads(json.dumps(doc))
    bad["compaction"]["auc_delta"] = bspec["compact_auc_delta_max"] * 3.0
    p2 = str(tmp_path / "over_auc.json")
    with open(p2, "w") as fh:
        json.dump(bad, fh)
    assert perf_gate.run_gate([p2], budgets) == 1
    # doctor 3: a lossy tier with no declared budget line fails loudly
    bad = json.loads(json.dumps(doc))
    bad["precision"]["f8"] = dict(bad["precision"]["bf16"])
    p3 = str(tmp_path / "no_budget.json")
    with open(p3, "w") as fh:
        json.dump(bad, fh)
    assert perf_gate.run_gate([p3], budgets) == 1
    # doctor 4: measured compaction delta above its own declared bound
    bad = json.loads(json.dumps(doc))
    bad["compaction"]["max_score_delta"] = \
        bad["compaction"]["declared_max_score_delta"] * 1.5
    p4 = str(tmp_path / "bound_broken.json")
    with open(p4, "w") as fh:
        json.dump(bad, fh)
    assert perf_gate.run_gate([p4], budgets) == 1


# ---- obs: tier split renders live and from raw events ----

def test_precision_tier_in_serving_block_and_died_run_recovery(model,
                                                               tmp_path):
    from lightgbm_tpu.obs.report import human_table, summarize
    b, X = model
    out = str(tmp_path / "prec.jsonl")
    tele = obs.configure(out=out, freq=1, entry="test_precision_obs")
    with Server(max_batch_wait_us=0) as srv:
        srv.register("m", b)
        srv.submit("m", X[:17], raw_score=True).result(timeout=60)
        srv.submit("m", X[:17], raw_score=True,
                   precision="bf16").result(timeout=60)
        srv.submit("m", X[:33], raw_score=True,
                   precision="bf16").result(timeout=60)
    summary = summarize(tele)
    prec = summary["serving"]["precisions"]
    assert prec["exact"] == {"requests": 1, "rows": 17}
    assert prec["bf16"] == {"requests": 2, "rows": 50}
    assert "precision tiers" in human_table(summary)
    tele.flush()
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    from lightgbm_tpu.obs.registry import read_events
    rebuilt = obs_report.summary_from_events(read_events(out))
    assert rebuilt["serving"]["precisions"]["bf16"] == \
        {"requests": 2, "rows": 50}
    assert rebuilt["serving"]["precisions"]["exact"] == \
        {"requests": 1, "rows": 17}
    obs.disable()
