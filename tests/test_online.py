"""Online learning subsystem (lightgbm_tpu/online): train-while-serve.

Pins the round-17 invariants:

- RowBuffer bounded-buffer + ingested/trained/dropped accounting;
- RetrainPolicy trigger precedence incl. the quality plane's
  ``level == "alert"`` hook;
- the warm-start continuation contract: ``train(k)`` -> publish ->
  continue-to-``k+m`` is BYTE-identical to the checkpoint-resume path at
  the same boundary (bagging on — absolute-iteration clocks);
- the long-run acceptance loop: fixed-qps traffic while the trainer
  publishes >= 3 generations (>= 1 drift-triggered), 0 dropped requests,
  0 steady-state recompiles outside swap warmup, every response
  bit-exact vs the generation that served it, and
  ``seconds_behind``/``rows_behind`` reset on each publish;
- refit-mode republish as a pure jit-cache hit (0 recompiles incl. the
  swap);
- rows_behind surfacing: /metrics gauge, summary quality + online
  blocks, and ``tools/obs_report.py`` died-run recovery.
"""
import os
import sys
import time

import numpy as np
import pytest

from lightgbm_tpu import obs, serve_and_train
from lightgbm_tpu.boosting import create_boosting
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.objective import create_objective
from lightgbm_tpu.online import OnlineController, RetrainPolicy, RowBuffer
from lightgbm_tpu.online.controller import WINDOW_SUFFIX
from lightgbm_tpu.utils.log import LightGBMError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_xy(seed, n=400, shift=None):
    rng = np.random.RandomState(seed)
    X = rng.uniform(-2, 2, size=(n, 5))
    if shift is not None:
        X[:, 0] = rng.uniform(*shift, size=n)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 2)
         + 0.1 * rng.normal(size=n)).astype(np.float64)
    return X, y


def _bootstrap(seed=0, n=400, rounds=4, **params):
    X, y = _make_xy(seed, n)
    cfg = Config(objective="regression", num_leaves=8, min_data_in_leaf=5,
                 verbosity=-1, num_iterations=rounds, max_bin=63, **params)
    ds = BinnedDataset.from_matrix(X, label=y, max_bin=63,
                                   min_data_in_leaf=5)
    b = create_boosting(cfg.boosting, cfg, ds,
                        create_objective(cfg.objective, cfg))
    b.train()
    return b, ds, X, y


def _params(**over):
    p = {"objective": "regression", "verbosity": -1,
         "online_rounds": 2, "online_min_rows": 0, "online_interval_s": 0,
         "online_drift_trigger": False, "online_poll_s": 0.02,
         "max_batch_wait_us": 0}
    p.update(over)
    return p


@pytest.fixture(autouse=True)
def _no_leaked_run():
    yield
    obs.disable()


# ---- RowBuffer ----

def test_row_buffer_accounting():
    buf = RowBuffer(width=3, max_rows=100)
    assert buf.ingest(np.zeros((10, 3)), np.zeros(10)) == 10
    assert buf.rows_behind() == 10 and buf.buffered == 10
    X, y, w, taken = buf.window()
    assert len(X) == 10 and w is None and taken == 10
    buf.mark_trained(taken)
    assert buf.rows_behind() == 0
    # consumed rows remain buffered (sliding history) but are not behind
    assert buf.buffered == 10
    buf.ingest(np.ones((4, 3)), np.ones(4), weight=np.full(4, 2.0))
    X, y, w, taken = buf.window(max_rows=6)
    assert len(X) == 6 and taken == 4
    # weights fill with ones for weightless chunks
    assert w is not None and w[-1] == 2.0 and w[0] == 1.0


def test_row_buffer_bounded_drop_oldest():
    buf = RowBuffer(width=2, max_rows=10)
    buf.ingest(np.full((6, 2), 1.0), np.zeros(6))
    buf.ingest(np.full((6, 2), 2.0), np.zeros(6))
    # first chunk evicted: buffered stays bounded, dropped counted, and
    # rows_behind reflects only what can still be trained
    assert buf.buffered == 6
    assert buf.rows_dropped == 6
    assert buf.rows_behind() == 6
    X, _, _, taken = buf.window()
    assert np.all(X == 2.0) and taken == 6


def test_row_buffer_validation():
    buf = RowBuffer(width=3, max_rows=10)
    with pytest.raises(LightGBMError):
        buf.ingest(np.zeros((2, 4)), np.zeros(2))
    with pytest.raises(LightGBMError):
        buf.ingest(np.zeros((2, 3)), np.zeros(3))


# ---- RetrainPolicy ----

def test_policy_triggers_and_precedence():
    now = 1000.0
    p = RetrainPolicy(min_rows=100, interval_s=50.0, drift_trigger=True,
                      max_rows_behind=500, max_seconds_behind=200.0)
    # no fresh rows -> never fire, whatever else is true
    assert p.reason(0, 0.0, {"level": "alert", "rows": 9999},
                    now=now) is None
    alert = {"level": "alert", "rows": 1000}
    assert p.reason(1, now, alert, now=now) == "drift"
    # below the drift row floor the alert is noise
    assert p.reason(1, now, {"level": "alert", "rows": 10}, now=now) is None
    assert p.reason(600, now, None, now=now) == "freshness_rows"
    assert p.reason(1, now - 300, None, now=now) == "freshness_seconds"
    assert p.reason(150, now, None, now=now) == "rows"
    assert p.reason(1, now - 60, None, now=now) == "interval"
    assert p.reason(1, now, None, now=now) is None
    off = RetrainPolicy(min_rows=0, interval_s=0, drift_trigger=False)
    assert not off.active()
    assert RetrainPolicy(min_rows=1).active()


# ---- warm-start continuation contract ----

def test_warm_start_equivalence_checkpoint_resume(tmp_path):
    """train(k) -> publish -> continue-to-k+m is byte-identical to the
    checkpoint-resume path at the same boundary, with bagging ON: the
    continuation clock is absolute, so the stateless bagging hash and
    the config-keyed chunk partitioning reproduce the uninterrupted
    stream."""
    k, m = 4, 4

    def build(n_iter):
        return _bootstrap(seed=0, rounds=n_iter, bagging_fraction=0.8,
                          bagging_freq=1, snapshot_freq=2)

    # uninterrupted reference (bootstraps straight to k+m)
    ref, _, _, _ = build(k + m)
    ref_str = ref.save_model_to_string()

    # checkpoint-resume path: checkpoint at k, restore, finish
    a, _, _, _ = build(k)
    prefix = str(tmp_path / "ck")
    a.save_checkpoint(prefix)
    b2, ds2, _, _ = _fresh_untouched(k + m)
    assert b2.resume_from_checkpoint(prefix) == k
    b2.train()
    resume_str = b2.save_model_to_string()
    assert resume_str == ref_str

    # online warm-start path: publish the k-round model text, continue in
    # a FRESH booster through warm_start_continuation
    pub, _, _, _ = build(k)
    model_str = pub.save_model_to_string()
    c, ds_c, _, _ = _fresh_untouched(k + m)
    assert c.warm_start_continuation(model_str, train_data=ds_c,
                                     objective=c.objective) == k
    c.train()
    assert c.save_model_to_string() == ref_str == resume_str


def _fresh_untouched(n_iter):
    """A booster configured for n_iter total iterations but with NONE
    trained yet (the _bootstrap helper trains eagerly)."""
    X, y = _make_xy(0, 400)
    cfg = Config(objective="regression", num_leaves=8, min_data_in_leaf=5,
                 verbosity=-1, num_iterations=n_iter, max_bin=63,
                 bagging_fraction=0.8, bagging_freq=1, snapshot_freq=2)
    ds = BinnedDataset.from_matrix(X, label=y, max_bin=63,
                                   min_data_in_leaf=5)
    b = create_boosting(cfg.boosting, cfg, ds,
                        create_objective(cfg.objective, cfg))
    return b, ds, X, y


# ---- window dataset ----

def test_window_dataset_clones_mappers_with_window_occupancy():
    b, ds, X, y = _bootstrap()
    ctrl = OnlineController.__new__(OnlineController)
    ctrl.base_ds = ds
    Xw, yw = _make_xy(3, 120)
    wds = OnlineController._window_dataset(ctrl, Xw, yw, None)
    assert wds.num_data == 120
    # shared layout: same bounds/EFB grouping, so routing is identical
    assert wds.group_idx is ds.group_idx
    for i, (m_base, m_win) in enumerate(zip(ds.bin_mappers,
                                            wds.bin_mappers)):
        assert m_win is not m_base
        if not m_base.is_trivial:
            assert m_win.num_bin == m_base.num_bin
            np.testing.assert_array_equal(m_win.bin_upper_bound,
                                          m_base.bin_upper_bound)
            # window occupancy, not the base training occupancy
            want = np.bincount(m_base.values_to_bins(Xw[:, i]),
                               minlength=m_base.num_bin)
            np.testing.assert_array_equal(m_win.cnt_in_bin, want)
            assert int(m_win.cnt_in_bin.sum()) == 120
    # the base mappers were never mutated
    assert all(m.cnt_in_bin is None or int(m.cnt_in_bin.sum()) != 120
               for m in ds.bin_mappers if not m.is_trivial)


# ---- controller basics ----

def test_controller_extend_cycle_and_stats(tmp_path):
    b, ds, X, y = _bootstrap()
    ctrl = serve_and_train(b, train_set=ds, params=_params(), name="m")
    try:
        assert ctrl.generation == 1
        it0 = ctrl.booster.iter_
        Xf, yf = _make_xy(5, 150)
        ctrl.ingest(Xf, yf)
        assert ctrl.run_cycle("manual")
        st = ctrl.stats()
        assert st["generation"] == 2 and st["cycles"] == 1
        assert st["iterations"] == it0 + 2  # online_rounds=2, extend
        assert st["rows_behind"] == 0
        assert st["rows_ingested"] == 150 and st["rows_trained"] == 150
        # the published generation is frozen: further trainer mutation
        # must not change what serves
        ref = ctrl.predict(X[:8].astype(np.float32))
        Xf2, yf2 = _make_xy(6, 150)
        ctrl.ingest(Xf2, yf2)
        assert ctrl.run_cycle("manual")
        assert ctrl.generation == 3
        got = ctrl.predict(X[:8].astype(np.float32))
        assert not np.array_equal(ref, got)  # new generation serves
    finally:
        ctrl.close()
    assert ctrl.stats()["serving"]["dropped"] == 0


def test_online_update_param_validated():
    with pytest.raises(LightGBMError):
        Config(online_update="nope")


def test_empty_window_cycle_is_noop():
    b, ds, X, y = _bootstrap()
    ctrl = serve_and_train(b, train_set=ds, params=_params(), name="m")
    try:
        assert not ctrl.run_cycle("manual")          # nothing buffered
        ctrl.ingest(*_make_xy(5, 50))
        assert ctrl.run_cycle("manual")
        # fresh-rows guard: the auto-trigger path cannot double-fire on
        # the unchanged window
        assert not ctrl.run_cycle("flush", require_fresh=True)
        assert ctrl.generation == 2
    finally:
        ctrl.close()


def test_ingest_width_validation():
    b, ds, X, y = _bootstrap()
    ctrl = serve_and_train(b, train_set=ds, params=_params(), name="m")
    try:
        with pytest.raises(LightGBMError):
            ctrl.ingest(np.zeros((3, 2)), np.zeros(3))
    finally:
        ctrl.close()


def test_refit_mode_republish_pure_cache_hit():
    """online_update=refit keeps the ensemble shapes constant, so the
    whole cycle — window binning aside, after one warmup cycle — and the
    republish are recompile-free."""
    from lightgbm_tpu.obs import recompile
    b, ds, X, y = _bootstrap()
    ctrl = serve_and_train(
        b, train_set=ds,
        params=_params(online_update="refit", online_window_rows=128),
        name="m")
    try:
        ref = ctrl.predict(X[:8].astype(np.float32))
        # warmup cycle compiles the refit-path programs once
        ctrl.ingest(*_make_xy(5, 128))
        assert ctrl.run_cycle("warmup")
        ctrl.predict(X[:8].astype(np.float32))
        base = recompile.total()
        ctrl.ingest(*_make_xy(6, 128))
        assert ctrl.run_cycle("steady")
        got = ctrl.predict(X[:8].astype(np.float32))
        assert recompile.total() - base == 0, \
            "refit republish recompiled"
        assert ctrl.generation == 3
        assert ctrl.booster.num_trees == b.num_trees  # structure frozen
        assert not np.array_equal(ref, got)  # values did move
    finally:
        ctrl.close()


# ---- window persistence / resume plumbing ----

def test_window_persist_roundtrip(tmp_path):
    prefix = str(tmp_path / "model.txt")
    b, ds, X, y = _bootstrap()
    ctrl = serve_and_train(b, train_set=ds, params=_params(), name="m",
                           checkpoint_prefix=prefix, publish_out=prefix)
    try:
        Xf, yf = _make_xy(5, 60)
        meta = {"cycle": 1, "reason": "t", "taken": 60, "mode": "extend",
                "target_iterations": 6, "rows_ingested": 60,
                "rows_trained": 0, "rows_dropped": 0}
        ctrl._persist_window(Xf, yf, None, meta)
        path = prefix + WINDOW_SUFFIX
        assert os.path.exists(path)
        pending = ctrl._load_pending_window()
        assert pending is not None
        np.testing.assert_array_equal(pending["X"], Xf)
        np.testing.assert_array_equal(pending["y"], yf)
        assert pending["w"] is None
        assert pending["meta"] == meta
        # a cycle consumes the file
        ctrl.ingest(Xf, yf)
        assert ctrl.run_cycle("manual")
        assert not os.path.exists(path)
        # every publish persisted the generation model text
        assert os.path.exists(prefix)
    finally:
        ctrl.close()


def test_publish_out_warm_start(tmp_path):
    """A restarted process warm-starts from the newest published
    generation (never from scratch): the rebuilt controller's trainer
    starts at the published iteration count and generation 1 serves the
    published model's scores."""
    prefix = str(tmp_path / "model.txt")
    b, ds, X, y = _bootstrap()
    ctrl = serve_and_train(b, train_set=ds, params=_params(), name="m",
                           publish_out=prefix)
    ctrl.ingest(*_make_xy(5, 100))
    assert ctrl.run_cycle("manual")
    want = ctrl.predict(X[:8].astype(np.float32))
    iters = ctrl.booster.iter_
    ctrl.close()

    b2, ds2, _, _ = _bootstrap()   # the same bootstrap a rerun would do
    ctrl2 = serve_and_train(b2, train_set=ds2, params=_params(), name="m",
                            publish_out=prefix)
    try:
        assert ctrl2.booster.iter_ == iters
        got = ctrl2.predict(X[:8].astype(np.float32))
        np.testing.assert_array_equal(want, got)
    finally:
        ctrl2.close()


# ---- drift-triggered refit, end to end ----

def test_drift_triggered_cycle_comes_back_clean(tmp_path):
    """Shifted traffic -> quality alert -> the policy fires with
    trigger="drift" -> the new generation (trained on the shifted
    window) scores the same traffic as quiet."""
    tele = obs.configure(out=str(tmp_path / "drift.jsonl"), freq=1)
    b, ds, X, y = _bootstrap(n=600)
    ctrl = serve_and_train(
        b, train_set=ds,
        params=_params(online_drift_trigger=True, online_poll_s=0.02,
                       online_rounds=2),
        name="m")
    try:
        # shifted feature-0 traffic, served AND (labels known) ingested
        Xs, ys = _make_xy(21, 600, shift=(5.0, 9.0))
        for lo in range(0, 600, 100):
            ctrl.predict(Xs[lo:lo + 100].astype(np.float32))
        ctrl.ingest(Xs, ys)
        from lightgbm_tpu.obs import quality as _quality
        mon = _quality.monitor(tele)
        snap = mon.snapshot()["models"]["m"]
        assert snap["level"] == "alert", snap
        deadline = time.time() + 60
        while ctrl.generation < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert ctrl.generation >= 2, (ctrl.stats(), ctrl.last_error)
        assert ctrl.last_trigger == "drift"
        # the new generation's baseline is its own (shifted) training
        # window: the same traffic now reads clean
        for lo in range(0, 600, 100):
            ctrl.predict(Xs[lo:lo + 100].astype(np.float32))
        snap2 = mon.snapshot()["models"]["m"]
        assert snap2["generation"] >= 2
        assert snap2["level"] == "ok", snap2
    finally:
        ctrl.close()
        obs.disable()


# ---- the long-run acceptance loop ----

def test_long_run_acceptance(tmp_path):
    """One process serves fixed-qps traffic while the trainer publishes
    >= 3 generations (>= 1 drift-triggered): 0 dropped requests, every
    response bit-exact vs the generation that served it, 0 steady-state
    recompiles outside swap warmup, and seconds_behind/rows_behind reset
    on each publish."""
    from lightgbm_tpu.obs import recompile
    tele = obs.configure(out=str(tmp_path / "long.jsonl"), freq=1)
    b, ds, X, y = _bootstrap(n=600)
    # warm every rung the open-loop traffic can coalesce into (1/17/64-row
    # requests merge past 128 under backlog): publishes pre-compile both,
    # so the steady windows between swaps stay recompile-free
    ctrl = serve_and_train(
        b, train_set=ds,
        params=_params(online_min_rows=150, online_drift_trigger=True,
                       online_poll_s=0.02, online_rounds=2),
        name="m", warm=(128, 1024))
    pool = X[:64].astype(np.float32)
    sizes = (1, 17, 64)
    responses = []
    refs = []

    def capture_refs():
        refs.append({n: ctrl.predict(pool[:n], raw_score=True)
                     for n in sizes})

    def paced_traffic(n_req, qps=120.0, rows=None):
        """Open-loop fixed-qps submits; responses validated at the end."""
        interval = 1.0 / qps
        t0 = time.perf_counter()
        futs = []
        rng = np.random.RandomState(len(responses))
        for i in range(n_req):
            target = t0 + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            n = int(sizes[rng.randint(len(sizes))])
            src = pool if rows is None else rows
            futs.append((n, ctrl.submit(src[:n], raw_score=True)))
        for n, f in futs:
            responses.append((n, f.result(timeout=120)))

    def wait_generation(g, timeout=90.0):
        deadline = time.time() + timeout
        while ctrl.generation < g and time.time() < deadline:
            if ctrl.cycle_failures:
                raise AssertionError(ctrl.last_error)
            time.sleep(0.02)
        assert ctrl.generation >= g, ctrl.stats()
        capture_refs()

    try:
        capture_refs()
        # two cadence-triggered generations under paced traffic
        for phase in (31, 32):
            Xf, yf = _make_xy(phase, 160)
            ctrl.ingest(Xf, yf)
            paced_traffic(60)
            wait_generation(len(refs) + 1)
        # one drift-triggered generation: shifted traffic observed by the
        # quality plane, a small (below-cadence) labeled batch ingested
        Xs, ys = _make_xy(33, 600, shift=(5.0, 9.0))
        for lo in range(0, 600, 100):
            ctrl.predict(Xs[lo:lo + 100].astype(np.float32))
        ctrl.ingest(Xs[:100], ys[:100])
        paced_traffic(40)
        wait_generation(4)
        assert ctrl.cycles >= 3
        assert "drift" in [ctrl.last_trigger] \
            or tele.registry.snapshot()["counters"].get(
                "online_trigger_drift"), ctrl.stats()

        # freshness resets on publish: the quality snapshot's rows_behind
        # reads 0 and seconds_behind is fresh
        from lightgbm_tpu.obs import quality as _quality
        snap = _quality.monitor(tele).snapshot()["models"]["m"]
        assert snap["rows_behind"] == 0, snap
        assert snap["seconds_behind"] is not None \
            and snap["seconds_behind"] < 60, snap
        assert snap["generation"] == ctrl.generation

        # steady state outside swap warmup: a post-publish serving window
        # compiles nothing (gauge-pinned)
        for n in sizes:
            ctrl.predict(pool[:n], raw_score=True)
        base = recompile.total()
        paced_traffic(40)
        assert recompile.total() - base == 0, recompile.counts()

        # every accepted response is bit-exact vs ONE published
        # generation's reference scores
        bad = sum(1 for n, got in responses
                  if not any(np.array_equal(got, r[n]) for r in refs))
        assert bad == 0, "%d/%d responses matched no generation" \
            % (bad, len(responses))
        assert len(responses) == 200  # 60 + 60 + 40 paced + 40 steady
        st = ctrl.stats()
        assert st["serving"]["dropped"] == 0
        assert st["serving"]["registry"]["swaps"] >= 3
    finally:
        ctrl.close()
        obs.disable()


# ---- observability surfacing ----

def test_rows_behind_gauge_summary_and_recovery(tmp_path):
    jsonl = str(tmp_path / "onl.jsonl")
    tele = obs.configure(out=jsonl, freq=1)
    b, ds, X, y = _bootstrap()
    ctrl = serve_and_train(b, train_set=ds, params=_params(), name="m")
    try:
        ctrl.ingest(*_make_xy(5, 120))
        assert ctrl.run_cycle("manual")
        ctrl.ingest(*_make_xy(6, 30))   # 30 rows now behind
        # serve some traffic so the monitor folds rows + emits drift
        # breadcrumbs (which carry rows_behind for died-run recovery)
        for _ in range(3):
            ctrl.predict(X[:32].astype(np.float32))

        from lightgbm_tpu.obs.exporter import render_prometheus
        from lightgbm_tpu.obs import quality as _quality
        mon = _quality.monitor(tele)
        snap = mon.snapshot()
        assert snap["models"]["m"]["rows_behind"] == 30
        prom = render_prometheus(tele.registry.snapshot(), quality=snap)
        assert 'lgbm_tpu_model_rows_behind{model="m"} 30.0' in prom, prom
        assert 'lgbm_tpu_model_seconds_behind{model="m"}' in prom

        from lightgbm_tpu.obs.report import summarize
        summary = summarize(tele)
        assert summary["quality"]["models"]["m"]["rows_behind"] == 30
        onl = summary["online"]
        assert onl["cycles"] == 1 and onl["generation"] == 2
        assert onl["triggers"] == {"manual": 1}
        assert onl["train_s"]["count"] == 1
    finally:
        ctrl.close()
        obs.disable()

    # died-run recovery: the raw events alone rebuild rows_behind and the
    # online block
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from obs_report import summary_from_events
    rec = summary_from_events(obs.iter_events(jsonl))
    assert rec["online"]["cycles"] == 1
    assert rec["online"]["triggers"] == {"manual": 1}
    assert rec["quality"]["models"]["m"].get("rows_behind") == 30
    from lightgbm_tpu.obs.report import human_table
    table = human_table(rec)
    assert "online:" in table and "rows_behind" in table


def test_healthz_online_block():
    from lightgbm_tpu.obs.exporter import health_snapshot
    b, ds, X, y = _bootstrap()
    ctrl = serve_and_train(b, train_set=ds, params=_params(), name="m")
    try:
        health = health_snapshot()
        onl = health.get("online")
        assert onl is not None, sorted(health)
        assert onl["trainer_alive"] is True
        assert onl["generation"] == 1 and onl["state"] in ("idle",
                                                           "training",
                                                           "publishing")
        assert onl["rows_behind"] == 0
    finally:
        ctrl.close()
    assert "online" not in health_snapshot()


def test_online_events_and_spans(tmp_path):
    jsonl = str(tmp_path / "spans.jsonl")
    obs.configure(out=jsonl, freq=1)
    b, ds, X, y = _bootstrap()
    ctrl = serve_and_train(b, train_set=ds, params=_params(), name="m")
    try:
        ctrl.ingest(*_make_xy(5, 80))
        assert ctrl.run_cycle("manual")
    finally:
        ctrl.close()
        obs.disable()
    evs = obs.read_events(jsonl)
    cyc = [e for e in evs if e["kind"] == "online_cycle"]
    assert len(cyc) == 1
    e = cyc[0]
    assert e["trigger"] == "manual" and e["generation"] == 2 \
        and e["rows"] == 80 and e["rows_behind"] == 0
    spans = {e.get("name") for e in evs if e["kind"] == "span"}
    # trainer lifecycle spans: the cycle with its train/publish children
    assert {"online_cycle", "online_train", "online_publish"} <= spans


def test_no_telemetry_run_makes_no_quality_state():
    assert obs.active() is None
    b, ds, X, y = _bootstrap()
    ctrl = serve_and_train(b, train_set=ds, params=_params(), name="m")
    try:
        ctrl.ingest(*_make_xy(5, 60))
        assert ctrl.run_cycle("manual")
        ctrl.predict(X[:8].astype(np.float32))
        assert obs.active() is None  # nothing configured a run behind us
    finally:
        ctrl.close()


def test_task_alias_and_engine_export():
    import lightgbm_tpu as lgb
    assert lgb.serve_and_train is serve_and_train
    cfg = Config(task="online")
    assert cfg.task == "online"
    cfg = Config(task="serve_and_train")
    assert cfg.task == "online"
