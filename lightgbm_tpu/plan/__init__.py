"""Unified kernel-planning layer with a persisted autotuner (round 18).

One planner — (shape-class, dtype/packing, classes, device_kind, VMEM
budget) -> typed :class:`~.planner.Plan` — covering the four dispatch
sites that previously each reinvented VMEM budgeting: the fused-split
bucket schedule, the level-mode window ladder, the histogram layout
chooser, and the predict tree-block sizing.  See ``plan/planner.py`` for
the design contract, ``plan/state.py`` for the resolution entry point,
``plan/cache.py`` for the persisted tuned-plan cache and its fail-safe
fallback, ``plan/autotune.py`` for the empirical mode, and
``plan/device_specs.py`` for the per-device hardware tables.

IMPORT DISCIPLINE: ``core/histogram.py`` and ``core/predict_fused.py``
import ``plan.device_specs`` at module load, which executes this package
``__init__`` first — so everything here is lazy (PEP 562).  Importing
``lightgbm_tpu.plan`` pulls in no jax, no core, nothing.
"""
from __future__ import annotations

_SUBMODULES = ("autotune", "cache", "device_specs", "planner", "state")

# the package-level convenience API, resolved lazily
_LAZY = {
    "Plan": ("planner", "Plan"),
    "ShapeClass": ("planner", "ShapeClass"),
    "analytic_plan": ("planner", "analytic_plan"),
    "plan_key": ("planner", "plan_key"),
    "shape_class": ("planner", "shape_class"),
    "validate_plan": ("planner", "validate_plan"),
    "resolve": ("state", "resolve"),
    "configure": ("state", "configure"),
    "configure_from_config": ("state", "configure_from_config"),
    "pinned": ("state", "pinned"),
    "stamp": ("state", "stamp"),
    "fallback_count": ("cache", "fallback_count"),
    "default_cache_path": ("cache", "default_cache_path"),
}

__all__ = sorted(set(_SUBMODULES) | set(_LAZY))


def __getattr__(name):
    import importlib
    if name in _SUBMODULES:
        return importlib.import_module("." + name, __name__)
    if name in _LAZY:
        mod, attr = _LAZY[name]
        return getattr(importlib.import_module("." + mod, __name__), attr)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))


def __dir__():
    return __all__
