"""Feature-histogram construction — the hottest op (SURVEY.md §3.1).

Counterpart of the reference's histogram kernels: the CPU ``Bin::ConstructHistogram``
family (src/io/dense_bin.hpp:48, src/io/dataset.cpp:1265,1370) and the OpenCL
``histogram256`` kernels (src/treelearner/ocl/histogram256.cl:317).

TPU-first design: TPUs have no fast scatter-add, so instead of per-workgroup local
histograms with float atomics (histogram256.cl:100-130) the histogram is computed as
a one-hot contraction per feature tile — compare a bin tile against an iota to get a
``[rows, bins]`` one-hot and contract it with the (grad, hess) pair on the MXU/VPU.
Accumulation order is fixed by the sequential TPU grid, so results are deterministic
(unlike the reference GPU path's atomic adds).

Two channels per bin — (sum_grad, sum_hess) — matching the reference's 16-byte
histogram entry (bin.h:41 ``HistogramSumReducer``); bin counts are derived from
hessians downstream exactly like feature_histogram.hpp:535 ``cnt_factor``.

Leaf membership / bagging are handled by pre-masking grad/hess to zero, so the
kernel itself is mask-free and shape-static.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128


def _pad_bins(num_bins: int) -> int:
    return max(_LANE, -(-num_bins // _LANE) * _LANE)


def histogram_xla(bins: jax.Array, values: jax.Array, num_bins: int) -> jax.Array:
    """Reference implementation via segment-sum; runs on any backend.

    bins: [N, F] integer; values: [N, 2] f32 (grad, hess; pre-masked).
    Returns [F, 2, num_bins] f32.
    """
    n, f = bins.shape
    ids = bins.astype(jnp.int32) + jnp.arange(f, dtype=jnp.int32)[None, :] * num_bins
    vals = jnp.broadcast_to(values[:, None, :], (n, f, 2)).reshape(n * f, 2)
    hist = jax.ops.segment_sum(vals, ids.reshape(-1), num_segments=f * num_bins)
    return hist.reshape(f, num_bins, 2).transpose(0, 2, 1)


def _hist_kernel(bins_ref, vals_ref, out_ref, *, num_features: int, num_bins: int):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bins = bins_ref[...].astype(jnp.int32)          # [Nt, F]
    vals = vals_ref[...]                            # [Nt, 2]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, num_bins), 1)

    # static unroll over features (Mosaic TC has no dynamic_slice); each step is
    # a [2, Nt] x [Nt, B] one-hot contraction on the MXU
    for f in range(num_features):
        col = bins[:, f:f + 1]                                      # [Nt, 1]
        onehot = (col == iota).astype(jnp.float32)                  # [Nt, B]
        acc = jax.lax.dot_general(vals, onehot, (((0,), (0,)), ((), ())),
                                  precision=jax.lax.Precision.HIGHEST,
                                  preferred_element_type=jnp.float32)  # [2, B]
        out_ref[f, :, :] += acc


@functools.partial(jax.jit, static_argnames=("num_bins", "row_tile", "interpret"))
def histogram_pallas(bins: jax.Array, values: jax.Array, num_bins: int,
                     row_tile: int = 2048, interpret: bool = False) -> jax.Array:
    """Pallas TPU histogram: grid over row tiles, one-hot contraction per feature.

    bins: [N, F] int (any small int dtype); values: [N, 2] f32.
    Returns [F, 2, num_bins] f32.  N must be a multiple of row_tile (pad with
    zero-valued rows).
    """
    n, f = bins.shape
    assert n % row_tile == 0, "pad rows to a multiple of row_tile"
    grid = (n // row_tile,)
    kernel = functools.partial(_hist_kernel, num_features=f, num_bins=num_bins)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile, f), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, 2), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((f, 2, num_bins), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f, 2, num_bins), jnp.float32),
        interpret=interpret,
    )(bins.astype(jnp.int32), values)


def _pick_tile(n: int) -> int | None:
    for tile in (4096, 2048, 1024):
        if n % tile == 0:
            return tile
    return None


def build_histogram(bins: jax.Array, values: jax.Array, num_bins: int,
                    use_pallas: bool | None = None) -> jax.Array:
    """Dispatch: Pallas on TPU, segment-sum elsewhere.  [F, 2, B] f32 output."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        tile = _pick_tile(bins.shape[0])
        if tile is not None:
            return histogram_pallas(bins, values, num_bins, row_tile=tile)
    return histogram_xla(bins, values, num_bins)


def _hist_kernel_bounded(cnt_ref, bins_ref, vals_ref, out_ref, *,
                         num_features: int, num_bins: int, row_tile: int):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    # tiles beyond the active row count skip both compute and (via the
    # cnt-dependent index_map) the HBM fetch — cost scales with cnt, not N
    @pl.when(pl.program_id(0) * row_tile < cnt_ref[0])
    def _accum():
        bins = bins_ref[...].astype(jnp.int32)
        vals = vals_ref[...]
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, num_bins), 1)
        for f in range(num_features):
            onehot = (bins[:, f:f + 1] == iota).astype(jnp.float32)
            acc = jax.lax.dot_general(vals, onehot, (((0,), (0,)), ((), ())),
                                      precision=jax.lax.Precision.HIGHEST,
                                      preferred_element_type=jnp.float32)
            out_ref[f, :, :] += acc


@functools.partial(jax.jit, static_argnames=("num_bins", "row_tile"))
def histogram_pallas_bounded(bins: jax.Array, values: jax.Array, num_bins: int,
                             cnt: jax.Array, row_tile: int = 4096) -> jax.Array:
    """Histogram over the first ``cnt`` rows of a compacted matrix.

    The counterpart of the reference's per-leaf ``data_indices`` histograms
    (dense_bin.hpp:48 ConstructHistogram over ordered indices): rows of one leaf
    are gathered to the front, ``cnt`` rides scalar prefetch, and tiles past the
    count are skipped.  values beyond cnt MUST already be zeroed (safety net for
    the partial tile)."""
    n, f = bins.shape
    assert n % row_tile == 0, "pad rows to a multiple of row_tile"
    grid = (n // row_tile,)

    def _in_idx(i, cnt_ref):
        # revisit block 0 for skipped tiles: Mosaic elides the re-fetch
        return (jnp.where(i * row_tile < cnt_ref[0], i, 0), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile, f), _in_idx),
            pl.BlockSpec((row_tile, 2), _in_idx),
        ],
        out_specs=pl.BlockSpec((f, 2, num_bins), lambda i, cnt_ref: (0, 0, 0)),
    )
    kernel = functools.partial(_hist_kernel_bounded, num_features=f,
                               num_bins=num_bins, row_tile=row_tile)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((f, 2, num_bins), jnp.float32),
    )(cnt.reshape(1).astype(jnp.int32), bins.astype(jnp.int32), values)


def build_histogram_bounded(bins: jax.Array, values: jax.Array, num_bins: int,
                            cnt: jax.Array,
                            use_pallas: bool | None = None) -> jax.Array:
    """Bounded-row histogram dispatch; values past cnt must be zero."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        tile = _pick_tile(bins.shape[0])
        if tile is not None:
            return histogram_pallas_bounded(bins, values, num_bins, cnt,
                                            row_tile=tile)
    return histogram_xla(bins, values, num_bins)
