"""Every tools/*.py must import and answer --help.

PERF.md's measurement protocol names these tools per claim (BENCH_r07
convention); a tool that no longer imports — a renamed kernel symbol, a
moved module — silently rots the protocol.  This smoke test executes each
tool as __main__ with --help inside ONE subprocess (a single jax import
amortized over all of them), asserting argparse answers with a usage
string and exit code 0 before any device work or heavy allocation starts.
"""
import glob
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = sorted(os.path.basename(p)
               for p in glob.glob(os.path.join(REPO, "tools", "*.py")))

_DRIVER = r"""
import contextlib, io, os, runpy, sys
repo = sys.argv[1]
failures = []
for name in sys.argv[2:]:
    path = os.path.join(repo, "tools", name)
    sys.argv = [path, "--help"]
    buf = io.StringIO()
    code = None
    try:
        with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(buf):
            runpy.run_path(path, run_name="__main__")
    except SystemExit as e:  # argparse --help exits 0
        code = 0 if e.code in (0, None) else e.code
    except BaseException as e:  # noqa: BLE001
        failures.append("%s: %r" % (name, e))
        continue
    out = buf.getvalue()
    if code != 0:
        failures.append("%s: exit code %r (%s)" % (name, code, out[:200]))
    elif "usage" not in out.lower():
        failures.append("%s: no usage text in --help output: %r"
                        % (name, out[:200]))
    else:
        print("ok:", name)
if failures:
    print("FAILURES:")
    for f in failures:
        print(" ", f)
    sys.exit(1)
"""


def test_every_tool_answers_help():
    assert TOOLS, "no tools found"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-c", _DRIVER, REPO] + TOOLS,
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert p.returncode == 0, p.stdout + p.stderr
    for name in TOOLS:
        assert "ok: %s" % name in p.stdout, (name, p.stdout, p.stderr)


def test_gen_params_check_in_sync():
    """``gen_params.py --check`` is the staleness tripwire for the
    embedded ``_params_meta.py`` tail: it must pass on the committed
    tree, and fail loudly when the meta file drifts from the generator
    (a hand-edited tail is exactly the rot it exists to catch)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    tool = os.path.join(REPO, "tools", "gen_params.py")
    p = subprocess.run([sys.executable, tool, "--check"],
                       capture_output=True, text=True, timeout=120,
                       env=env, cwd=REPO)
    assert p.returncode == 0, p.stdout + p.stderr
    # a drifted meta file must flunk the check, naming the problem
    import tempfile
    with open(os.path.join(REPO, "lightgbm_tpu", "_params_meta.py")) as fh:
        meta = fh.read()
    with tempfile.NamedTemporaryFile("w", suffix=".py",
                                     delete=False) as tmp:
        tmp.write(meta.replace("'hist_precision'", "'hist_drifted'", 1))
        stale = tmp.name
    try:
        p = subprocess.run([sys.executable, tool, "--check",
                            "--meta", stale],
                           capture_output=True, text=True, timeout=120,
                           env=env, cwd=REPO)
        assert p.returncode != 0, p.stdout + p.stderr
    finally:
        os.unlink(stale)


def test_bench_split_cost_importable():
    """The round-7 acceptance tool parses args and exposes its sweep/fit
    entry points without touching jax at import time."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_split_cost
    finally:
        sys.path.pop(0)
    args = bench_split_cost.parse_args(["--min-pow", "8", "--max-pow", "9"])
    assert args.min_pow == 8 and args.max_pow == 9
    icept, slope = bench_split_cost.fit_line([1.0, 2.0, 3.0],
                                             [3.0, 5.0, 7.0])
    assert icept == pytest.approx(1.0) and slope == pytest.approx(2.0)
