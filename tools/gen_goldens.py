"""Regenerate tests/data/golden_metrics.json entries from the reference CLI.

Runs the reference LightGBM CLI (built from /root/reference, see PERF notes:
/tmp/refbuild/lightgbm) on the bundled example datasets for every parity
config and captures its per-iteration metric lines.  The four example
configs' goldens were captured in round 3; round 4 adds the remaining
training modes (VERDICT item 6): dart, goss, rf, monotone constraints,
forced splits, and a sparse LibSVM load.

Usage:  python tools/gen_goldens.py [path-to-reference-cli]
"""
import json
import os
import re
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_EXAMPLES = "/root/reference/examples"
GOLDEN = os.path.join(REPO, "tests", "data", "golden_metrics.json")
ITERS = (10, 25, 50, 100)

# name -> (example dir for data files, overrides)
CONFIGS = {
    "dart": ("binary_classification", {
        "boosting_type": "dart", "drop_rate": 0.1, "skip_drop": 0.5}),
    "goss": ("binary_classification", {
        "boosting_type": "goss", "bagging_freq": 0, "bagging_fraction": 1.0}),
    "rf": ("binary_classification", {
        "boosting_type": "rf", "bagging_freq": 1, "bagging_fraction": 0.9,
        "feature_fraction": 0.9}),
    "monotone": ("regression", {
        "monotone_constraints": ",".join(
            ["1", "-1", "1", "0", "0", "-1"] + ["0"] * 22)}),
    "forced_splits": ("binary_classification", {
        "forcedsplits_filename": "__FORCED__",
        "feature_fraction": 1.0, "bagging_freq": 0, "bagging_fraction": 1.0}),
    # binary objective over the lambdarank LibSVM file: a deterministic
    # sparse-ingestion parity pin (relevance>0 counts as positive)
    "sparse_binary": ("lambdarank", {
        "objective": "binary", "metric": "binary_logloss,auc",
        "num_leaves": 31, "min_data_in_leaf": 20,
        "feature_fraction": 1.0, "bagging_freq": 0, "bagging_fraction": 1.0}),
}

FORCED_JSON = {
    "feature": 1, "threshold": 0.5,
    "left": {"feature": 5, "threshold": 1.0},
}

DATA_FILES = {
    "binary_classification": ("binary.train", "binary.test"),
    "regression": ("regression.train", "regression.test"),
    "lambdarank": ("rank.train", "rank.test"),
}


def run_reference(cli, name, example, overrides, workdir):
    base = os.path.join(REF_EXAMPLES, example, "train.conf")
    params = {}
    with open(base) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if "=" in line:
                k, v = line.split("=", 1)
                params[k.strip()] = v.strip()
    train, test = DATA_FILES[example]
    params["data"] = os.path.join(REF_EXAMPLES, example, train)
    params["valid_data"] = os.path.join(REF_EXAMPLES, example, test)
    params["num_trees"] = str(max(ITERS))
    params["metric_freq"] = "1"
    params["is_training_metric"] = "true"
    params.pop("output_model", None)
    for k, v in overrides.items():
        params[k] = str(v)
    if params.get("forcedsplits_filename") == "__FORCED__":
        fpath = os.path.join(workdir, "forced.json")
        with open(fpath, "w") as fh:
            json.dump(FORCED_JSON, fh)
        params["forcedsplits_filename"] = fpath
    conf = os.path.join(workdir, name + ".conf")
    with open(conf, "w") as fh:
        for k, v in params.items():
            fh.write("%s = %s\n" % (k, v))
    out = subprocess.run([cli, "config=" + conf], capture_output=True,
                         text=True, cwd=workdir, check=True)
    log = out.stdout + out.stderr
    # [LightGBM] [Info] Iteration:10, training auc : 0.9...
    metrics = {}
    for m in re.finditer(
            r"Iteration:\s*(\d+),\s*(\S+)\s+(\S+)\s*:\s*([-\d.eE+]+)", log):
        it, ds, metric, val = m.groups()
        metrics.setdefault(it, {})["%s %s" % (ds, metric)] = float(val)
    return {str(i): metrics[str(i)] for i in ITERS}


def main():
    import argparse
    ap = argparse.ArgumentParser(
        description="regenerate tests/data/golden_metrics.json from the "
                    "reference CLI")
    ap.add_argument("cli", nargs="?", default="/tmp/refbuild/lightgbm")
    cli = ap.parse_args().cli
    with open(GOLDEN) as fh:
        golden = json.load(fh)
    with tempfile.TemporaryDirectory() as workdir:
        for name, (example, overrides) in CONFIGS.items():
            print("running reference:", name)
            golden[name] = run_reference(cli, name, example, overrides,
                                         workdir)
    with open(GOLDEN, "w") as fh:
        json.dump(golden, fh, indent=1)
        fh.write("\n")
    print("wrote", GOLDEN)


if __name__ == "__main__":
    main()
