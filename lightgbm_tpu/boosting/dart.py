"""DART: dropouts meet multiple additive regression trees
(src/boosting/dart.hpp:23-211)."""
from __future__ import annotations

import numpy as np

from .gbdt import GBDT
from ..obs import active as _telemetry_active
from ..utils.log import Log


class DART(GBDT):
    fuse_iters = False
    lazy_trees = False  # dropout shrinks/re-adds host trees every iteration
    # dropout rescales OLD trees' leaf values in place and appends to the
    # tree-weight history — effects the pre-chunk score/model refs cannot
    # undo, so score corruption stops at detection (gbdt._guard_chunk_scores)
    _prechunk_rollback_safe = False

    def __init__(self, config, train_data=None, objective=None, mesh=None):
        self._drop_rng = np.random.RandomState(int(config.drop_seed))
        self.tree_weight = []
        self.sum_weight = 0.0
        self.drop_index = []
        self._score_is_dropped = False
        super().__init__(config, train_data, objective, mesh=mesh)

    def sub_model_name(self) -> str:
        return "tree"

    def _extra_train_state(self):
        """Dropout state a bit-exact resume needs: the drop RNG stream and
        the per-tree weight history driving non-uniform drop probabilities
        (dart.hpp:76-86).  Without these a resumed run drops different
        trees and silently diverges."""
        from ..checkpoint import encode_rng_state
        return {"drop_rng": encode_rng_state(self._drop_rng),
                "tree_weight": [float(w) for w in self.tree_weight],
                "sum_weight": float(self.sum_weight)}

    def _restore_extra_train_state(self, extra):
        from ..checkpoint import decode_rng_state
        self._drop_rng.set_state(decode_rng_state(extra["drop_rng"]))
        self.tree_weight = [float(w) for w in extra.get("tree_weight", [])]
        self.sum_weight = float(extra.get("sum_weight", 0.0))
        self.drop_index = []
        self._score_is_dropped = False

    def _get_gradients(self):
        # drop trees once per iteration before computing gradients (dart.hpp:76-86)
        if not self._score_is_dropped:
            self._dropping_trees()
            self._score_is_dropped = True
        return super()._get_gradients()

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        self._score_is_dropped = False
        ret = super().train_one_iter(gradients, hessians)
        if ret:
            return ret
        self._normalize()
        if not self.config.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False

    def _dropping_trees(self) -> None:
        self.drop_index = []
        cfg = self.config
        if self._drop_rng.uniform() >= cfg.skip_drop:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                if self.sum_weight > 0:
                    inv_avg = len(self.tree_weight) / self.sum_weight
                    if cfg.max_drop > 0:
                        drop_rate = min(drop_rate,
                                        cfg.max_drop * inv_avg / self.sum_weight)
                    for i in range(self.iter_):
                        if (self._drop_rng.uniform()
                                < drop_rate * self.tree_weight[i] * inv_avg):
                            self.drop_index.append(self.num_init_iteration + i)
                            if len(self.drop_index) >= cfg.max_drop > 0:
                                break
            else:
                if cfg.max_drop > 0 and self.iter_ > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / self.iter_)
                for i in range(self.iter_):
                    if self._drop_rng.uniform() < drop_rate:
                        self.drop_index.append(self.num_init_iteration + i)
                        if len(self.drop_index) >= cfg.max_drop > 0:
                            break
        # remove dropped trees from the training score (dart.hpp:129-137):
        # negate the tree, then add it to the score
        for i in self.drop_index:
            for c in range(self.num_tree_per_iteration):
                tree = self.models[i * self.num_tree_per_iteration + c]
                tree.shrink(-1.0)
                self._add_tree_score_train(tree, c)
        kdrop = len(self.drop_index)
        tele = _telemetry_active()
        if tele is not None:
            tele.histogram("dart_dropped_trees").observe(kdrop)
            # JSONL growth bounded by the telemetry_freq cadence like
            # engine.train's iteration events; the histogram sees every drop
            if self.iter_ % tele.freq == 0:
                tele.event("dart_drop", iteration=int(self.iter_),
                           dropped=int(kdrop))
        if not self.config.xgboost_dart_mode:
            self.shrinkage_rate = self.config.learning_rate / (1.0 + kdrop)
        else:
            self.shrinkage_rate = (self.config.learning_rate if kdrop == 0 else
                                   self.config.learning_rate
                                   / (self.config.learning_rate + kdrop))

    def _normalize(self) -> None:
        """Re-add dropped trees normalized to k/(k+1) weight (dart.hpp:139-183)."""
        k = float(len(self.drop_index))
        cfg = self.config
        for i in self.drop_index:
            for c in range(self.num_tree_per_iteration):
                idx = i * self.num_tree_per_iteration + c
                tree = self.models[idx]
                if not cfg.xgboost_dart_mode:
                    # tree currently at -w; scale leaf values to w*k/(k+1)
                    tree.shrink(1.0 / (k + 1.0))     # -> -w/(k+1)
                    for vs in self.valid_sets:
                        self._add_tree_score_valid(idx, tree, c, vs)
                    tree.shrink(-k)                  # -> w*k/(k+1)
                    self._add_tree_score_train(tree, c)
                else:
                    tree.shrink(self.shrinkage_rate)
                    for vs in self.valid_sets:
                        self._add_tree_score_valid(idx, tree, c, vs)
                    tree.shrink(-k / cfg.learning_rate)
                    self._add_tree_score_train(tree, c)
            if not cfg.uniform_drop:
                j = i - self.num_init_iteration
                if not cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[j] / (k + 1.0)
                    self.tree_weight[j] *= k / (k + 1.0)
                else:
                    self.sum_weight -= self.tree_weight[j] / (k + cfg.learning_rate)
                    self.tree_weight[j] *= k / (k + cfg.learning_rate)
