"""Public API behavioral tests, modeled on the reference's python suite
(tests/python_package_test/test_engine.py, test_sklearn.py, test_basic.py):
train real models on synthetic data and assert metric thresholds/invariants.
"""
import pickle

import numpy as np
import pytest

import lightgbm_tpu as lgb


def make_binary(n=2000, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    logit = X[:, 0] * 2 + X[:, 1] ** 2 - 1 + rng.normal(scale=0.5, size=n)
    y = (logit > 0).astype(np.float64)
    return X, y


def make_regression(n=2000, f=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 3 + np.sin(X[:, 1]) + rng.normal(scale=0.2, size=n)
    return X, y


def test_train_binary_with_valid_and_evals_result():
    X, y = make_binary()
    Xt, yt = make_binary(seed=1)
    train_data = lgb.Dataset(X, label=y)
    valid_data = lgb.Dataset(Xt, label=yt, reference=train_data)
    evals_result = {}
    params = {"objective": "binary", "metric": ["binary_logloss", "auc"],
              "num_leaves": 15, "verbosity": -1}
    bst = lgb.train(params, train_data, num_boost_round=30,
                    valid_sets=[valid_data], valid_names=["valid"],
                    evals_result=evals_result, verbose_eval=False)
    assert bst.current_iteration() == 30
    assert "valid" in evals_result
    assert len(evals_result["valid"]["binary_logloss"]) == 30
    assert evals_result["valid"]["auc"][-1] > 0.85
    assert evals_result["valid"]["binary_logloss"][-1] < \
        evals_result["valid"]["binary_logloss"][0]
    preds = bst.predict(Xt)
    acc = np.mean((preds > 0.5) == yt)
    assert acc > 0.85


def test_early_stopping():
    X, y = make_binary()
    Xt, yt = make_binary(seed=1)
    train_data = lgb.Dataset(X, label=y)
    valid_data = lgb.Dataset(Xt, label=yt, reference=train_data)
    params = {"objective": "binary", "metric": "binary_logloss",
              "num_leaves": 31, "learning_rate": 0.5, "verbosity": -1}
    bst = lgb.train(params, train_data, num_boost_round=200,
                    valid_sets=[valid_data], early_stopping_rounds=5,
                    verbose_eval=False)
    assert bst.best_iteration > 0
    assert bst.current_iteration() < 200


def test_regression_and_model_roundtrip(tmp_path):
    X, y = make_regression()
    params = {"objective": "regression", "metric": "l2", "num_leaves": 31,
              "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=25,
                    verbose_eval=False)
    pred = bst.predict(X)
    mse = np.mean((pred - y) ** 2)
    assert mse < 0.5
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    np.testing.assert_allclose(bst2.predict(X), pred, rtol=1e-5)
    # model_to_string / model_from_string
    s = bst.model_to_string()
    bst3 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst3.predict(X), pred, rtol=1e-5)


def test_multiclass():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(1500, 8))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)
    params = {"objective": "multiclass", "num_class": 3,
              "metric": "multi_logloss", "num_leaves": 15, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20,
                    verbose_eval=False)
    pred = bst.predict(X)
    assert pred.shape == (1500, 3)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-5)
    acc = np.mean(np.argmax(pred, axis=1) == y)
    assert acc > 0.85


def test_lambdarank():
    rng = np.random.RandomState(7)
    n_q, per_q = 80, 20
    X = rng.normal(size=(n_q * per_q, 6))
    rel = np.clip((X[:, 0] + rng.normal(scale=0.5, size=len(X))) > 0.7, 0, 1)
    y = rel.astype(np.float64) * rng.randint(1, 4, size=len(X)) * rel
    group = np.full(n_q, per_q)
    params = {"objective": "lambdarank", "metric": "ndcg", "ndcg_eval_at": [5],
              "num_leaves": 15, "verbosity": -1}
    ds = lgb.Dataset(X, label=y, group=group)
    bst = lgb.train(params, ds, num_boost_round=20, verbose_eval=False)
    assert bst.current_iteration() == 20


def test_cv():
    X, y = make_binary(1000)
    params = {"objective": "binary", "metric": "binary_logloss",
              "num_leaves": 15, "verbosity": -1}
    ret = lgb.cv(params, lgb.Dataset(X, label=y), num_boost_round=10, nfold=3,
                 stratified=True, verbose_eval=False)
    assert "binary_logloss-mean" in ret
    assert "binary_logloss-stdv" in ret
    assert len(ret["binary_logloss-mean"]) == 10
    assert ret["binary_logloss-mean"][-1] < ret["binary_logloss-mean"][0]


def test_custom_fobj_feval():
    X, y = make_regression()

    def l2_obj(preds, dataset):
        grad = preds - dataset.get_label()
        hess = np.ones_like(grad)
        return grad, hess

    def l1_eval(preds, dataset):
        return "mae", float(np.mean(np.abs(preds - dataset.get_label()))), False

    train_data = lgb.Dataset(X, label=y)
    evals_result = {}
    bst = lgb.train({"num_leaves": 15, "verbosity": -1}, train_data,
                    num_boost_round=20, fobj=l2_obj, feval=l1_eval,
                    valid_sets=[train_data], valid_names=["train"],
                    evals_result=evals_result, verbose_eval=False)
    assert "mae" in evals_result["train"]
    assert evals_result["train"]["mae"][-1] < evals_result["train"]["mae"][0]
    # custom objective trains from 0 init score: compare raw predictions
    pred = bst.predict(X, raw_score=True)
    assert np.mean((pred - y) ** 2) < np.var(y)


def test_pickle_booster():
    X, y = make_binary(800)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    verbose_eval=False)
    dumped = pickle.dumps(bst)
    bst2 = pickle.loads(dumped)
    np.testing.assert_allclose(bst2.predict(X), bst.predict(X), rtol=1e-6)


def test_continued_training():
    X, y = make_regression()
    d1 = lgb.Dataset(X, label=y, free_raw_data=False)
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    bst1 = lgb.train(params, d1, num_boost_round=10, verbose_eval=False)
    mse1 = np.mean((bst1.predict(X) - y) ** 2)
    bst2 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10,
                     init_model=bst1, verbose_eval=False)
    assert bst2.current_iteration() == 20
    mse2 = np.mean((bst2.predict(X) - y) ** 2)
    assert mse2 < mse1


def test_pred_leaf():
    X, y = make_binary(500)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5,
                    verbose_eval=False)
    leaves = bst.predict(X, pred_leaf=True)
    assert leaves.shape == (500, 5)
    assert leaves.max() < 7


def test_pandas_and_categorical():
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(3)
    n = 1200
    cat = rng.randint(0, 4, size=n)
    num = rng.normal(size=n)
    y = (cat == 2).astype(float) * 2 + num + rng.normal(scale=0.1, size=n)
    df = pd.DataFrame({"c": pd.Categorical.from_codes(cat, ["a", "b", "c", "d"]),
                       "x": num})
    ds = lgb.Dataset(df, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=20,
                    verbose_eval=False)
    assert bst.feature_name() == ["c", "x"]
    dfp = pd.DataFrame({"c": pd.Categorical.from_codes(cat, ["a", "b", "c", "d"]),
                        "x": num})
    pred = bst.predict(dfp)
    assert np.mean((pred - y) ** 2) < np.var(y) * 0.5


def test_sklearn_classifier():
    X, y = make_binary()
    labels = np.where(y > 0, "pos", "neg")
    clf = lgb.LGBMClassifier(n_estimators=20, num_leaves=15)
    clf.fit(X, labels)
    assert set(clf.classes_) == {"pos", "neg"}
    proba = clf.predict_proba(X)
    assert proba.shape == (len(X), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    pred = clf.predict(X)
    assert np.mean(pred == labels) > 0.9
    imp = clf.feature_importances_
    assert imp.shape == (10,)
    assert imp[0] > 0


def test_sklearn_regressor_and_early_stopping():
    X, y = make_regression()
    Xt, yt = make_regression(seed=5)
    reg = lgb.LGBMRegressor(n_estimators=100, num_leaves=31,
                            learning_rate=0.2)
    reg.fit(X, y, eval_set=[(Xt, yt)], eval_metric="l2",
            early_stopping_rounds=5, verbose=False)
    assert reg.best_iteration_ > 0
    pred = reg.predict(Xt)
    assert np.mean((pred - yt) ** 2) < np.var(yt) * 0.3


def test_sklearn_multiclass():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(900, 6))
    y = np.array(["u", "v", "w"])[
        ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int))]
    clf = lgb.LGBMClassifier(n_estimators=15, num_leaves=15)
    clf.fit(X, y)
    assert clf.n_classes_ == 3
    proba = clf.predict_proba(X)
    assert proba.shape == (900, 3)
    assert np.mean(clf.predict(X) == y) > 0.8


def test_sklearn_ranker():
    rng = np.random.RandomState(1)
    X = rng.normal(size=(600, 5))
    y = np.clip(X[:, 0] + rng.normal(scale=0.3, size=600), 0, None)
    y = np.digitize(y, [0.5, 1.2]).astype(float)
    group = np.full(30, 20)
    rk = lgb.LGBMRanker(n_estimators=10, num_leaves=7)
    rk.fit(X, y, group=group)
    assert rk.booster_.current_iteration() == 10


def test_dataset_save_binary(tmp_path):
    X, y = make_binary(300)
    ds = lgb.Dataset(X, label=y)
    path = str(tmp_path / "data.bin")
    ds.save_binary(path)
    from lightgbm_tpu.io.dataset import BinnedDataset
    loaded = BinnedDataset.load_binary(path)
    assert loaded.num_data == 300
    np.testing.assert_array_equal(loaded.binned, ds.handle.binned)


def test_reset_parameter_callback():
    X, y = make_regression()
    lrs = [0.3] * 5 + [0.1] * 5
    evals_result = {}
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "metric": "l2", "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    valid_sets=[lgb.Dataset(X, label=y)],
                    callbacks=[lgb.reset_parameter(learning_rate=lrs)],
                    evals_result=evals_result, verbose_eval=False)
    assert bst.current_iteration() == 10
