# Parse the reference-format model text into a per-node table — role of the
# reference R-package/R/lgb.model.dt.tree.R (theirs walks the JSON dump;
# this walks the text model's per-tree arrays directly, so it needs no JSON
# parser and works on any saved model file).

.lgbmtpu_tree_blocks <- function(model_str) {
  lines <- strsplit(model_str, "\n", fixed = TRUE)[[1L]]
  starts <- grep("^Tree=", lines)
  ends <- c(starts[-1L] - 1L, length(lines))
  Map(function(s, e) lines[s:e], starts, ends)
}

.lgbmtpu_field <- function(block, name) {
  row <- grep(paste0("^", name, "="), block, value = TRUE)
  if (length(row) == 0L) return(numeric(0))
  txt <- sub(paste0("^", name, "="), "", row[1L])
  if (!nzchar(txt)) return(numeric(0))
  as.numeric(strsplit(txt, " ", fixed = TRUE)[[1L]])
}

#' Model structure as one data.frame row per node (internal + leaf)
#' @export
lgb.model.dt.tree <- function(booster = NULL, model_str = NULL) {
  if (is.null(model_str)) model_str <- lgb.model.to.string(booster)
  out <- list()
  for (ti in seq_along(blocks <- .lgbmtpu_tree_blocks(model_str))) {
    b <- blocks[[ti]]
    nl <- as.integer(.lgbmtpu_field(b, "num_leaves"))
    split_feature <- as.integer(.lgbmtpu_field(b, "split_feature"))
    threshold <- .lgbmtpu_field(b, "threshold")
    split_gain <- .lgbmtpu_field(b, "split_gain")
    internal_count <- .lgbmtpu_field(b, "internal_count")
    leaf_value <- .lgbmtpu_field(b, "leaf_value")
    leaf_count <- .lgbmtpu_field(b, "leaf_count")
    ni <- max(nl - 1L, 0L)
    if (ni > 0L) {
      out[[length(out) + 1L]] <- data.frame(
        tree_index = ti - 1L,
        node_type = "internal",
        node_index = seq_len(ni) - 1L,
        split_feature = split_feature[seq_len(ni)],
        threshold = threshold[seq_len(ni)],
        split_gain = split_gain[seq_len(ni)],
        count = if (length(internal_count)) internal_count[seq_len(ni)]
                else NA_real_,
        value = NA_real_,
        stringsAsFactors = FALSE)
    }
    out[[length(out) + 1L]] <- data.frame(
      tree_index = ti - 1L,
      node_type = "leaf",
      node_index = seq_len(max(nl, 1L)) - 1L,
      split_feature = NA_integer_,
      threshold = NA_real_,
      split_gain = NA_real_,
      count = if (length(leaf_count)) leaf_count[seq_len(max(nl, 1L))]
              else NA_real_,
      value = leaf_value[seq_len(max(nl, 1L))],
      stringsAsFactors = FALSE)
  }
  do.call(rbind, out)
}
