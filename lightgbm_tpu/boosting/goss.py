"""GOSS: gradient-based one-side sampling (src/boosting/goss.hpp:25-185).

Keep the top_rate fraction by |grad*hess|, sample other_rate from the rest and
amplify their grad/hess by (1-top_rate)/other_rate.  Expressed as a row weight
mask (0 / 1 / multiplier) folded into grad/hess, matching the reference's
in-place gradient scaling (goss.hpp:117-121).

Round 12: the top-k selection runs ON DEVICE — ``jax.lax.top_k`` over the
|grad*hess| key replaces the host ``np.argsort`` round-trip (the full-n
top_k is XLA's stable descending sort: ties broken toward the lower index,
exactly ``np.argsort(-g, kind="stable")``, pinned by
tests/test_goss_device.py).  Only the "other" subsample's POSITIONS still
come from the host RandomState — same call with the same arguments as
before, so the bagging RNG stream (and with it checkpoint resume
bit-exactness) is unchanged.  The host path is retained as a fallback
(``LIGHTGBM_TPU_GOSS_HOST=1`` or any selection failure) and is bit-equal to
the device path.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .gbdt import GBDT
from ..obs import active as _telemetry_active
from ..utils.log import Log


class GOSS(GBDT):
    fuse_iters = False
    def __init__(self, config, train_data=None, objective=None, mesh=None):
        super().__init__(config, train_data, objective, mesh=mesh)
        if config.top_rate + config.other_rate > 1.0:
            Log.fatal("top_rate + other_rate cannot be larger than 1.0 in GOSS")
        if config.top_rate <= 0.0 or config.other_rate <= 0.0:
            Log.fatal("top_rate and other_rate must be positive in GOSS")
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            Log.fatal("Cannot use bagging in GOSS")
        Log.info("Using GOSS")
        self._goss_multiplier = None
        self._goss_device = os.environ.get("LIGHTGBM_TPU_GOSS_HOST",
                                           "0") != "1"

    def _bagging(self, it: int) -> None:
        # GOSS resamples every iteration once warmed up (goss.hpp:133-136:
        # no subsampling for the first 1/learning_rate iterations)
        self.bag_mask = None
        self.bag_data_cnt = self.num_data
        self._goss_multiplier = None
        if it < int(1.0 / self.config.learning_rate):
            return
        self._needs_goss = True

    def _select_weights_device(self, key, top_k: int,
                               sampled: np.ndarray, multiply: float):
        """Device-side selection: full-n ``lax.top_k`` gives the stable
        descending order (== np.argsort(-key, kind="stable")); the top_k
        prefix keeps weight 1, the host-sampled positions of the remainder
        get the amplification weight.  No key/order round-trips the host."""
        n = key.shape[0]
        _, order = jax.lax.top_k(key, n)
        w = jnp.zeros((n,), jnp.float32)
        w = w.at[order[:top_k]].set(1.0)
        if len(sampled):
            other_idx = order[top_k:][jnp.asarray(sampled, jnp.int32)]
            w = w.at[other_idx].set(np.float32(multiply))
        return w

    def _select_weights_host(self, key: np.ndarray, top_k: int,
                             sampled: np.ndarray, multiply: float):
        """Host fallback (the pre-round-12 path), bit-equal to the device
        selection on the same key."""
        n = len(key)
        order = np.argsort(-key, kind="stable")
        w = np.zeros(n, dtype=np.float32)
        w[order[:top_k]] = 1.0
        if len(sampled):
            w[order[top_k:][sampled]] = multiply
        return jnp.asarray(w)

    def _adjust_gradients_for_bagging(self, grad, hess):
        if getattr(self, "_needs_goss", False):
            self._needs_goss = False
            key = jnp.abs(grad * hess).sum(axis=0)
            n = self.num_data
            top_k = max(1, int(n * self.config.top_rate))
            other_k = max(1, int(n * self.config.other_rate))
            rest_n = n - top_k
            # the "other" positions come from the SAME host RandomState call
            # as always — the bagging RNG stream checkpoints replay is
            # untouched by where the sort runs
            sampled = self._bag_rng.choice(
                rest_n, size=min(other_k, rest_n), replace=False)
            multiply = (n - top_k) / max(other_k, 1)
            if self._goss_device:
                try:
                    w = self._select_weights_device(key, top_k, sampled,
                                                    multiply)
                except Exception as exc:  # degraded-mode idiom (round 11):
                    # selection failure falls back to the bit-equal host
                    # path instead of killing the run
                    Log.warning("device GOSS selection failed (%s); falling "
                                "back to the host path", exc)
                    self._goss_device = False
            if not self._goss_device:
                w = self._select_weights_host(np.asarray(key), top_k,
                                              sampled, multiply)
            self.bag_data_cnt = top_k + len(sampled)
            self.bag_mask = None  # weights are folded into grad/hess below
            tele = _telemetry_active()
            if tele is not None:
                tele.gauge("goss_top_k").set(top_k)
                tele.gauge("goss_other_k").set(len(sampled))
                # JSONL growth bounded by the telemetry_freq cadence like
                # engine.train's iteration events; gauges always current
                if self.iter_ % tele.freq == 0:
                    tele.event("goss_select", iteration=int(self.iter_),
                               top_k=int(top_k),
                               other_k=int(len(sampled)),
                               multiplier=float(multiply))
            wj = w[None, :]
            return grad * wj, hess * wj
        return grad, hess
