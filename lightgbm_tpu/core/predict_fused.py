"""Fused inference engine: tree-blocked path-matrix prediction.

core/predict.py's `lax.scan` runs ONE [N,M]@[M,L] contraction per tree — T
dispatch-serialized steps for embarrassingly tree-parallel work, each far too
small to fill the MXU.  Here G trees are stacked per scan block and one
batched `dot_general` ([N, G, M] x [G, M, L], batched over the block axis —
the block-diagonal form of a single [N, G*M] @ [G*M, G*L] contraction)
replaces G steps: the scan shrinks to T/G steps of G-fold larger matmuls.
G is chosen so a block's path matrices stay VMEM-resident
(:func:`tree_block` — the same trace-static sizing discipline as
``partition.fused_bucket_plan``).

Three serving mechanisms ride on top:

- **binned fast path** (:class:`BinnedEnsembleArrays`): when the caller holds
  the training-format u8/u16 row store (refit, training-data scoring,
  ``Dataset``-backed predict), ``go_left`` is an integer compare against
  host-prebinned thresholds — the semantics of ``tree_learner._route_left``
  (tree.h:262-331 *Inner decisions) — skipping the f32 gather/NaN pipeline
  and reading 1 byte/feature instead of 4.
- **bounded shape buckets**: rows pad to a fixed ladder
  (:data:`PREDICT_BUCKETS`) instead of unbounded pow2 targets, and batches
  beyond the largest bucket stream through it in fixed-shape chunks — so
  steady-state serving compiles at most ``len(PREDICT_BUCKETS)`` programs per
  model, ever.  :class:`FusedPredictor` additionally caches the stacked
  device ensemble so repeat calls re-stack nothing.
- **sharded batch predict** lives in ``parallel.learners.sharded_predict``
  (rows over the mesh via shard_map; body built from :func:`scan_blocks`).

Every path is BIT-exact vs the per-tree ``predict_ensemble`` scan: hits are
small-integer f32 sums (exact in any accumulation order), ``match`` is an
exact one-hot, so each tree contributes exactly its leaf value, and the [N]
score accumulation + early-stop checks replay the per-tree order inside an
unrolled per-block loop.  Pinned by tests/test_predict_fused.py the way
tests/test_partition_buckets.py pins the split-kernel variants.
"""
from __future__ import annotations

import functools
import time
from typing import List, NamedTuple, Optional

import jax
import jax.experimental  # noqa: F401  (enable_x64 for the contrib path)
import jax.numpy as jnp
import numpy as np

from ..io.binning import BinType, MissingType
from ..obs import active as _telemetry_active
from ..obs import annotate as _annotate
from ..obs import compile as _compile
from ..obs import recompile as _recompile
from ..plan import device_specs as _device_specs
from ..plan import state as _plan_state
from ..utils.timer import FunctionTimer
from .predict import (EnsembleArrays, _path_matrix, decide_raw,
                      stack_ensemble_host)
from .tree import K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK, Tree

# path-matrix VMEM budget per scan block (f32 bytes) and the block-width cap;
# the same discipline as partition.fused_bucket_plan: sizes are host-static,
# derived only from the model shape, so the dispatch never retraces.  The
# budget constant moved to plan/device_specs.py (round 18, one source of
# truth per device_kind); a tuned/pinned kernel plan overrides it through
# plan/state.py at stack time.
BLOCK_VMEM_BYTES = _device_specs.PREDICT_BLOCK_VMEM_BYTES
BLOCK_MAX = 64

# fixed row-padding ladder: any batch size compiles at most len() programs
# per model; batches beyond the top bucket stream through it in fixed-shape
# chunks, so steady-state serving NEVER recompiles.
PREDICT_BUCKETS = (128, 1024, 8192, 65536, 524288)


def tree_block(t: int, m: int, l: int,
               vmem_bytes: Optional[int] = None,
               precision: str = "exact") -> int:
    """Trees per scan block: the largest count whose stacked [G, M, L] path
    matrices fit the block VMEM budget, rebalanced so the final block is
    not ragged (T=100 at cap 32 -> 4 blocks of 25, zero pad trees).

    The budget defaults through the kernel planner (round 18): a pinned
    or tuned plan's ``predict_block_vmem_bytes`` wins, else the
    device-spec constant — byte-equal to the historical sizing when no
    plan cache is engaged.  The bf16 tier's path matrices are 2 bytes per
    element, so the same VMEM budget admits ~2x the trees per block —
    bf16 stackings get their OWN G (and their own plan site,
    ``predict_fused_bf16``), never the exact tier's."""
    if vmem_bytes is None:
        vmem_bytes = _plan_state.predict_block_vmem() or BLOCK_VMEM_BYTES
    per_tree = max(m * l * (2 if precision == "bf16" else 4), 1)
    cap = max(1, min(BLOCK_MAX, int(vmem_bytes) // per_tree, max(t, 1)))
    n_blocks = -(-max(t, 1) // cap)
    return -(-max(t, 1) // n_blocks)


def shape_bucket(n: int) -> int:
    """Smallest ladder bucket holding ``n`` rows (top bucket for chunking)."""
    for b in PREDICT_BUCKETS:
        if n <= b:
            return b
    return PREDICT_BUCKETS[-1]


class BinnedEnsembleArrays(NamedTuple):
    """Stacked per-tree arrays for the binned row store, [T, M] per node
    (or [T/G, G, M] when blocked).  Thresholds are host-prebinned; the
    decide is ``tree_learner._route_left`` vectorized over (row, node)."""
    column: jax.Array        # [T, M] i32 — the bin matrix (group) column
    thr_bin: jax.Array       # [T, M] i32
    default_left: jax.Array  # [T, M] bool
    missing_type: jax.Array  # [T, M] i32 (io.binning.MissingType)
    num_bin: jax.Array       # [T, M] i32 (feature bins, for unfold + NaN bin)
    default_bin: jax.Array   # [T, M] i32
    offset: jax.Array        # [T, M] i32 (EFB group code of feature bin 1)
    is_cat: jax.Array        # [T, M] bool
    cat_bitset: jax.Array    # [T, M, W] u32 left-BIN sets (W=0: no cat)
    path_sign: jax.Array     # [T, M, L] f32
    path_len: jax.Array      # [T, L] f32 (pad -1)
    leaf_value: jax.Array    # [T, L] f32


def stack_ensemble_binned_host(trees: List[Tree],
                               dataset) -> BinnedEnsembleArrays:
    """Host: prebin every node of ``trees`` against ``dataset``'s bin
    mappers / EFB group layout (the per-node mapping of
    ``gbdt._tree_to_device``, batched into stacked numpy arrays).

    Any dataset sharing the training mappers (reference-aligned valid sets,
    subsets) routes identically; thresholds land on bin upper bounds so the
    binned decide is bit-parity with the raw-value decide on binned rows."""
    t_cnt = len(trees)
    m = max(max(t.num_leaves - 1, 1) for t in trees)
    l = max(t.num_leaves for t in trees)
    has_cat = any(t.num_cat > 0 for t in trees)
    w = 0
    if has_cat:
        cat_bins = [mp.num_bin for mp in dataset.bin_mappers
                    if mp.bin_type == BinType.CATEGORICAL]
        w = -(-max(cat_bins, default=32) // 32)
    col = np.zeros((t_cnt, m), dtype=np.int32)
    thr = np.zeros((t_cnt, m), dtype=np.int32)
    dl = np.zeros((t_cnt, m), dtype=bool)
    mt = np.zeros((t_cnt, m), dtype=np.int32)
    nb = np.ones((t_cnt, m), dtype=np.int32)
    db = np.zeros((t_cnt, m), dtype=np.int32)
    off = np.ones((t_cnt, m), dtype=np.int32)
    ic = np.zeros((t_cnt, m), dtype=bool)
    cb = np.zeros((t_cnt, m, w), dtype=np.uint32)
    ps = np.zeros((t_cnt, m, l), dtype=np.float32)
    pl = np.full((t_cnt, l), -1.0, dtype=np.float32)
    lv = np.zeros((t_cnt, l), dtype=np.float32)
    group_idx = dataset.group_idx
    for i, tree in enumerate(trees):
        ni = max(tree.num_leaves - 1, 0)
        for node in range(ni):
            f = int(tree.split_feature[node])
            mapper = dataset.bin_mappers[f]
            j = dataset.inner_feature_map[f]
            col[i, node] = 0 if group_idx is None else int(group_idx[j])
            off[i, node] = (1 if dataset.bin_offset is None
                            else int(dataset.bin_offset[j]))
            nb[i, node] = int(dataset.num_bin_per_feature[j])
            db[i, node] = int(mapper.default_bin)
            mt[i, node] = int(mapper.missing_type)
            dt = int(tree.decision_type[node])
            dl[i, node] = (dt & K_DEFAULT_LEFT_MASK) != 0
            if dt & K_CATEGORICAL_MASK:
                ic[i, node] = True
                ci = int(tree.threshold[node])
                lo = tree.cat_boundaries[ci]
                hi = tree.cat_boundaries[ci + 1]
                for wd in range(lo, hi):
                    word = int(tree.cat_threshold[wd])
                    for j2 in range(32):
                        if (word >> j2) & 1:
                            b = mapper.categorical_2_bin.get(
                                (wd - lo) * 32 + j2)
                            if b is not None:
                                cb[i, node, b >> 5] |= np.uint32(1 << (b & 31))
            else:
                thr[i, node] = mapper.value_to_bin(float(tree.threshold[node]))
        ps[i], pl[i] = _path_matrix(tree, m, l)
        lv[i, :tree.num_leaves] = tree.leaf_value[:tree.num_leaves]
    return BinnedEnsembleArrays(column=col, thr_bin=thr, default_left=dl,
                                missing_type=mt, num_bin=nb, default_bin=db,
                                offset=off, is_cat=ic, cat_bitset=cb,
                                path_sign=ps, path_len=pl, leaf_value=lv)


def decide_binned(B: jax.Array, ens: BinnedEnsembleArrays) -> jax.Array:
    """go_left [N, *TD, M] for binned rows B [N, num_groups]; node arrays
    shaped [*TD, M].  Integer compares only — ``_route_left`` +
    ``_unfold_bin`` semantics (NumericalDecisionInner tree.h:262-277,
    CategoricalDecisionInner :283-331: the NaN bin is never a member, so
    missing goes right)."""
    cols = jnp.take(B, ens.column, axis=1).astype(jnp.int32)  # [N, *TD, M]
    off = ens.offset[None]
    nb = ens.num_bin[None]
    # EFB group code -> feature bin (identity for singleton groups, off=1)
    bin_ = jnp.where((cols >= off) & (cols <= off + nb - 2),
                     cols - off + 1, 0)
    mt = ens.missing_type[None]
    is_missing = jnp.where(
        mt == int(MissingType.NAN), bin_ == nb - 1,
        jnp.where(mt == int(MissingType.ZERO),
                  bin_ == ens.default_bin[None], False))
    go_left = jnp.where(is_missing, ens.default_left[None],
                        bin_ <= ens.thr_bin[None])
    w = ens.cat_bitset.shape[-1]
    if w:
        # ONE gather over the word axis (program size O(1) in w, the
        # _route_left lookup shape); bins past the padded word range clamp
        # to zero words, i.e. not-a-member -> right, matching the host
        wi = bin_ >> 5
        word = jnp.take_along_axis(
            ens.cat_bitset[None], jnp.clip(wi, 0, w - 1)[..., None],
            axis=-1)[..., 0]
        bit = (word >> (bin_ & 31).astype(jnp.uint32)) & jnp.uint32(1)
        go_left = jnp.where(ens.is_cat[None], (wi < w) & (bit == 1), go_left)
    return go_left


def _decide(rows: jax.Array, blk) -> jax.Array:
    if isinstance(blk, BinnedEnsembleArrays):
        return decide_binned(rows, blk)
    return decide_raw(rows, blk.split_feature, blk.threshold,
                      blk.default_left, blk.missing_type, blk.is_cat,
                      blk.cat_bitset)


def _block(ens, g: int):
    """[T, ...] stacked numpy arrays -> [T/G, G, ...] blocks (pad trees are
    dead: all-zero path columns + path_len -1 never match, leaf values 0;
    the contrib schedule's pad trees are inactive-by-construction)."""
    t = ens[0].shape[0]
    tb = -(-t // g)
    pad = tb * g - t

    def one(name, a):
        if pad:
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            a = np.pad(a, widths, constant_values=(-1.0 if name == "path_len"
                                                   else 0))
        return jnp.asarray(a.reshape((tb, g) + a.shape[1:]))

    return type(ens)(*[one(n, a) for n, a in zip(ens._fields, ens)])


def _cast_lossy(ens):
    """The bf16 tier's device ensemble: ``path_sign`` and ``leaf_value``
    in bfloat16, EVERY routing array untouched.  Path signs are exactly
    ±1/0 in bf16, and the hit contraction accumulates in f32
    (``preferred_element_type``), so leaf HITS stay bit-exact vs the exact
    tier — only the leaf values (bf16-rounded) and the score accumulation
    (bf16 carry) are lossy, which is the declared error the budget gates."""
    return ens._replace(path_sign=ens.path_sign.astype(jnp.bfloat16),
                        leaf_value=ens.leaf_value.astype(jnp.bfloat16))


def stack_ensemble_blocked(trees: List[Tree], g: Optional[int] = None,
                           precision: str = "exact") -> EnsembleArrays:
    """Raw-feature blocked device ensemble ([T/G, G, ...] fields)."""
    host = stack_ensemble_host(trees)
    m, l = host.path_sign.shape[1], host.path_sign.shape[2]
    ens = _block(host, g or tree_block(len(trees), m, l,
                                       precision=precision))
    return _cast_lossy(ens) if precision == "bf16" else ens


def stack_ensemble_binned_blocked(trees: List[Tree], dataset,
                                  g: Optional[int] = None,
                                  precision: str = "exact"
                                  ) -> BinnedEnsembleArrays:
    """Binned blocked device ensemble ([T/G, G, ...] fields)."""
    host = stack_ensemble_binned_host(trees, dataset)
    m, l = host.path_sign.shape[1], host.path_sign.shape[2]
    ens = _block(host, g or tree_block(len(trees), m, l,
                                       precision=precision))
    return _cast_lossy(ens) if precision == "bf16" else ens


def scan_blocks(blocks, rows: jax.Array, *, early_stop_margin: float = -1.0,
                round_period: int = 10, want_leaf: bool = False):
    """The tree-blocked predict core (traceable; jitted wrappers below).

    One scan step per G-tree block: a shared decide, ONE batched
    [N, G, M] x [G, M, L] contraction, an exact one-hot match, then an
    unrolled per-tree accumulate that replays the per-tree scan's f32 add
    order and early-stop check positions bit-exactly (margin-based
    prediction early stop, prediction_early_stop.cpp:26-65).

    Dtype-generic over the ensemble's value arrays: the accumulate dtype
    is inferred from ``leaf_value`` (f32 exact tier / bf16 lossy tier),
    and every cast below is a no-op for f32 inputs, so the exact tier's
    jaxpr — and therefore its compiled program and its scores — is
    byte-identical to the pre-precision-axis one.  In the bf16 tier the
    hit sums still accumulate in f32 (small exact integers from ±1 bf16
    products) and ``match`` is still an exact one-hot, so ROUTING is
    bit-exact across tiers; only leaf rounding + the bf16 score carry
    differ."""
    n = rows.shape[0]
    g = blocks.path_len.shape[1]
    acc_dtype = blocks.leaf_value.dtype

    def block_step(carry, blk):
        score, active, idx = carry
        go_left = _decide(rows, blk)                        # [N, G, M]
        d = jnp.where(go_left, 1.0, -1.0).astype(blk.path_sign.dtype)
        hits = jax.lax.dot_general(
            d, blk.path_sign, (((2,), (1,)), ((1,), (0,))),
            preferred_element_type=jnp.float32)             # [G, N, L]
        match = (hits == blk.path_len[:, None, :]).astype(acc_dtype)
        contrib = jax.lax.dot_general(
            match, blk.leaf_value, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=acc_dtype)               # [G, N]
        for j in range(g):
            score = score + jnp.where(active, contrib[j], 0.0)
            if early_stop_margin >= 0:
                check = (idx + j + 1) % round_period == 0
                active = active & jnp.where(
                    check, 2.0 * jnp.abs(score) < early_stop_margin, True)
        if want_leaf:
            leaf = jnp.argmax(match, axis=2).astype(jnp.int32)  # [G, N]
            return (score, active, idx + g), leaf
        return (score, active, idx + g), None

    init = (jnp.zeros((n,), acc_dtype), jnp.ones((n,), bool), jnp.int32(0))
    (score, _, _), leaves = jax.lax.scan(block_step, init, blocks)
    if want_leaf:
        return score, jnp.transpose(leaves, (2, 0, 1)).reshape(n, -1)
    return score


@functools.partial(jax.jit, static_argnames=("early_stop_margin",
                                             "round_period", "want_leaf"))
def predict_blocked(blocks, rows, early_stop_margin: float = -1.0,
                    round_period: int = 10, want_leaf: bool = False):
    """Jitted tree-blocked predict over a raw [N, F] f32 chunk or a binned
    [N, num_groups] u8/u16 chunk (dispatch on the ensemble type)."""
    return scan_blocks(blocks, rows, early_stop_margin=early_stop_margin,
                       round_period=round_period, want_leaf=want_leaf)


def predict_compile_count() -> int:
    """Compiled-program count of the bucketed dispatch (the no-recompile
    serving contract is pinned against this going flat)."""
    return predict_blocked._cache_size()


@functools.partial(jax.jit, static_argnames=("early_stop_margin",
                                             "round_period", "want_leaf"))
def predict_scan_fallback(blocks, rows, early_stop_margin: float = -1.0,
                          round_period: int = 10, want_leaf: bool = False):
    """The degraded-mode predictor: the same scan core over a g=1 blocking
    (one tree per scan step — the pre-blocking per-tree scan), jitted into
    its OWN cache so a failure of the big blocked program (bucket compile,
    corrupted cache entry) cannot poison the fallback.  Bit-exact with the
    blocked path by the same argument every blocking is (integer hit sums,
    per-tree f32 add order replayed)."""
    return scan_blocks(blocks, rows, early_stop_margin=early_stop_margin,
                       round_period=round_period, want_leaf=want_leaf)


class FusedPredictor:
    """Device predictor for one class's tree sequence, stacked ONCE.

    The serving counterpart of the reference's cached ``SingleRowPredictor``
    (c_api.cpp:52-98), keyed by the boosters' ``EnsembleArrays`` identity:
    GBDT caches instances per (model range, generation, kind), so the hot
    path is pad-to-bucket + one cached-executable call."""

    def __init__(self, trees: List[Tree], dataset=None,
                 kind: str = "raw", precision: str = "exact") -> None:
        if kind not in ("raw", "binned"):
            raise ValueError("kind must be 'raw' or 'binned'")
        if precision not in ("exact", "bf16"):
            raise ValueError("precision must be 'exact' or 'bf16'")
        if kind == "binned" and dataset is None:
            raise ValueError("binned predictor needs the training dataset "
                             "layout (bin mappers + EFB groups)")
        self.kind = kind
        self.precision = precision
        # recompile/compile attribution site: the bf16 tier dispatches
        # through the SAME predict_blocked jit cache (dtype is part of the
        # aval, so tiers can never share a compiled program), but counts
        # under its own site name; `watch` below keys the shared cache so
        # the first bf16 dispatch doesn't inherit exact-tier compiles
        self._site = ("predict_blocked" if precision == "exact"
                      else "predict_blocked_bf16")
        self.n_trees = len(trees)
        # host trees retained for the contrib path: the SHAP schedule is
        # harvested lazily on the first predict_contrib call (score-only
        # serving pays nothing), and the host trees are the harvest input
        self._trees = list(trees)
        # lazily-built contrib program inputs per phi width, plus the g=1
        # degraded re-blocking (same discipline as _fb_ens)
        self._contrib: dict = {}
        self._fb_contrib: dict = {}
        self._contrib_warned = False
        # optional growth hook (serving registry residency accounting):
        # called with the byte size of lazily-built contrib ensembles
        self.on_grow = None
        # serving attribution: the ModelRegistry stamps the owning model's
        # name here so degraded-path fallbacks count per model, and hooks
        # on_fallback so each registry tallies only its OWN degradations
        # (the process-global resilience ledger can't distinguish two
        # registries holding a model under the same name)
        self.owner: Optional[str] = None
        self.on_fallback = None
        # keep the layout dataset alive: GBDT's predictor cache keys on
        # id(dataset), which must not be recycled while this entry lives
        self.layout_ds = dataset
        # degraded-mode serving: the g=1 fallback ensemble is derived from
        # the blocked one by reshape on first failure (no host trees
        # retained, no re-stacking; never an exception on the serving path)
        self._fb_ens = None
        self._fb_warned = False
        if kind == "raw":
            self.ens = (stack_ensemble_blocked(trees, precision=precision)
                        if trees else None)
        else:
            self.ens = (stack_ensemble_binned_blocked(
                trees, dataset, precision=precision) if trees else None)
        # plan provenance (round 18): which planner sized this stacking's
        # tree-block G — stamped once per run so BENCH/serving artifacts
        # record the plan behind every latency number.  The bf16 tier is
        # its own site (its 2-byte path matrices size a different G).
        tele = _telemetry_active()
        if tele is not None and self.ens is not None:
            _plan_state.stamp(
                tele, ("predict_fused" if precision == "exact"
                       else "predict_fused_bf16"),
                _plan_state.current_provenance(),
                key="t%d_g%d" % (self.n_trees,
                                 int(self.ens.path_len.shape[1])),
                store=self.kind, g=int(self.ens.path_len.shape[1]))

    def _prep_rows(self, X) -> np.ndarray:
        if self.kind == "raw":
            return np.ascontiguousarray(np.asarray(X, dtype=np.float32))
        X = np.ascontiguousarray(np.asarray(X))
        if X.dtype not in (np.uint8, np.uint16):
            raise TypeError("binned predictor wants the u8/u16 row store, "
                            "got %s" % X.dtype)
        return X

    def __call__(self, X, early_stop_margin: float = -1.0,
                 round_period: int = 10, want_leaf: bool = False):
        """[N] f64 raw scores (or [N, T] i32 leaf indices with want_leaf).

        Rows pad to the bucket ladder; batches beyond the top bucket stream
        through it in fixed-shape chunks (rows are independent, so early
        stop and leaves are chunk-local)."""
        n = len(X)
        if self.n_trees == 0 or n == 0:
            if want_leaf:
                return np.zeros((n, self.n_trees), dtype=np.int32)
            return np.zeros(n, dtype=np.float64)
        X = self._prep_rows(X)
        top = PREDICT_BUCKETS[-1]
        scores = np.empty(n, dtype=np.float64)
        leaves = (np.empty((n, self.n_trees), dtype=np.int32)
                  if want_leaf else None)
        tele = _telemetry_active()
        for lo in range(0, n, top):
            chunk = X[lo:lo + top]
            nc = len(chunk)
            bucket = shape_bucket(nc)
            if bucket > nc:
                chunk = np.concatenate(
                    [chunk, np.zeros((bucket - nc,) + chunk.shape[1:],
                                     dtype=chunk.dtype)])
            t0 = time.perf_counter()
            misses = 0
            try:
                with FunctionTimer("Predict::Fused(dispatch)"), \
                        _annotate("tree_block_predict"):
                    out = predict_blocked(
                        self.ens, jnp.asarray(chunk),
                        early_stop_margin=float(early_stop_margin),
                        round_period=int(round_period),
                        want_leaf=want_leaf)
                # growth of the bucketed dispatch's compiled-program count
                # is a recompile, attributed to this row bucket: the live
                # form of the "steady-state serving never recompiles"
                # invariant.  watch= keys the SHARED predict_blocked jit
                # cache, so each tier baselines against the same counter
                # instead of charging the other tier's compiles to itself.
                misses = _recompile.note_dispatch(self._site, bucket,
                                                  predict_compile_count(),
                                                  watch="predict_blocked")
            except Exception as exc:  # degraded serving: never an exception
                out = self._predict_degraded(
                    jnp.asarray(chunk), bucket, exc,
                    float(early_stop_margin), int(round_period), want_leaf)
            if tele is not None:
                dt = time.perf_counter() - t0
                hist = ("predict_dispatch_s_bucket_%d" % bucket
                        if self.precision == "exact" else
                        "predict_dispatch_bf16_s_bucket_%d" % bucket)
                tele.histogram(hist).observe(dt)
                tele.event("predict", rows=int(nc), bucket=int(bucket),
                           store=self.kind, trees=int(self.n_trees),
                           dt_s=dt, want_leaf=bool(want_leaf),
                           precision=self.precision)
                # compile accounting (obs/compile.py): every dispatch
                # wall feeds the steady estimate; miss-bearing ones are
                # priced against it (warm persistent-cache loads told
                # apart from true compiles by their tiny excess)
                _compile.note_dispatch(tele, self._site, bucket,
                                       dt, misses)
            if want_leaf:
                leaves[lo:lo + nc] = np.asarray(
                    out[1][:nc, :self.n_trees], dtype=np.int32)
            else:
                scores[lo:lo + nc] = np.asarray(out[:nc], dtype=np.float64)
        return leaves if want_leaf else scores

    # ---- SHAP contributions (core/predict_contrib.py) ----

    def contrib_blocks(self, ncol: int):
        """The stacked contrib program inputs for this predictor's trees
        (decide arrays + harvested TreeSHAP schedules, [T/G', G', ...]
        blocked at the contrib G'), built ONCE per phi width and cached —
        the FusedPredictor cache contract extended to explanations."""
        blocks = self._contrib.get(int(ncol))
        if blocks is None:
            from .predict_contrib import stack_contrib_blocked
            blocks, g = stack_contrib_blocked(
                self._trees, int(ncol),
                dataset=self.layout_ds if self.kind == "binned" else None,
                kind=self.kind)
            self._contrib[int(ncol)] = blocks
            if self.on_grow is not None:
                grew = sum(int(a.size * a.dtype.itemsize)
                           for part in blocks for a in part)
                self.on_grow(grew)
            tele = _telemetry_active()
            if tele is not None:
                # plan provenance: the contrib G is a round-18 plan site
                # of its own (sized on the REAL schedule footprint)
                _plan_state.stamp(
                    tele, "contrib_fused", _plan_state.current_provenance(),
                    key="t%d_g%d" % (self.n_trees, int(g)),
                    store=self.kind, g=int(g))
        return blocks

    def predict_contrib(self, X, ncol: int) -> np.ndarray:
        """[N, ncol] f64 SHAP contributions (last column = expected
        value) through the device path-decomposition kernel.  Rows pad to
        the same shape-bucket ladder as scores; batches beyond the top
        bucket stream through it in fixed-shape chunks; failures serve
        DEGRADED through the g=1 contrib program, and a failure of the
        harvest or of the degraded program itself falls all the way back
        to the host TreeSHAP scan (raw rows; counted — a raw contrib
        request is never an exception)."""
        if self.precision != "exact":
            raise ValueError("pred_contrib has no lossy tier: SHAP "
                             "contributions are exact (f64) only; use a "
                             "precision='exact' predictor")
        n = len(X)
        if self.n_trees == 0 or n == 0:
            return np.zeros((n, int(ncol)), dtype=np.float64)
        X = self._prep_rows(X)
        try:
            return self._predict_contrib_device(X, ncol)
        except Exception as exc:  # harvest or double-failure: host net
            return self._contrib_host_scan(X, ncol, exc)

    def _predict_contrib_device(self, X: np.ndarray,
                                ncol: int) -> np.ndarray:
        n = len(X)
        blocks = self.contrib_blocks(ncol)
        top = PREDICT_BUCKETS[-1]
        out = np.empty((n, int(ncol)), dtype=np.float64)
        tele = _telemetry_active()
        for lo in range(0, n, top):
            chunk = X[lo:lo + top]
            nc = len(chunk)
            bucket = shape_bucket(nc)
            if bucket > nc:
                chunk = np.concatenate(
                    [chunk, np.zeros((bucket - nc,) + chunk.shape[1:],
                                     dtype=chunk.dtype)])
            t0 = time.perf_counter()
            misses = 0
            try:
                from .predict_contrib import (contrib_compile_count,
                                              predict_contrib_blocked)
                with FunctionTimer("Predict::Contrib(dispatch)"), \
                        _annotate("contrib_fused"), \
                        jax.experimental.enable_x64():
                    # materialize INSIDE the x64 scope: slicing the f64
                    # result outside it would re-canonicalize avals to f32
                    res = np.asarray(predict_contrib_blocked(
                        blocks, jnp.asarray(chunk)))
                misses = _recompile.note_dispatch(
                    "predict_contrib_blocked", bucket,
                    contrib_compile_count())
            except Exception as exc:  # degraded serving: never an exception
                res = self._contrib_degraded(chunk, bucket, exc, ncol)
            if tele is not None:
                dt = time.perf_counter() - t0
                tele.histogram("contrib_latency_s_bucket_%d"
                               % bucket).observe(dt)
                tele.counter("contrib_calls").inc()
                tele.counter("contrib_rows").inc(int(nc))
                tele.event("contrib", rows=int(nc), bucket=int(bucket),
                           store=self.kind, trees=int(self.n_trees),
                           dt_s=dt)
                _compile.note_dispatch(tele, "predict_contrib_blocked",
                                       bucket, dt, misses)
            out[lo:lo + nc] = np.asarray(res[:nc], dtype=np.float64)
        return out

    def _contrib_degraded(self, chunk, bucket: int, exc: Exception,
                          ncol: int):
        """Serve the contrib chunk through the g=1 contrib program after
        the blocked dispatch failed — counted like every degraded path
        (``resilience.note_fallback`` + the ``contrib_fallbacks``
        counter), warned once per predictor."""
        from ..resilience import note_fallback
        from ..utils.log import Log
        from .predict_contrib import predict_contrib_scan_fallback
        if not self._contrib_warned:
            self._contrib_warned = True
            Log.warning("fused pred_contrib failed for bucket %d (%s: %s); "
                        "serving DEGRADED via the g=1 contrib program",
                        bucket, type(exc).__name__, exc)
        site = ("predict_contrib_blocked@%s" % self.owner if self.owner
                else "predict_contrib_blocked")
        note_fallback(site, reason="%s: %s" % (type(exc).__name__, exc),
                      bucket=int(bucket),
                      **({"model": self.owner} if self.owner else {}))
        tele = _telemetry_active()
        if tele is not None:
            tele.counter("contrib_fallbacks").inc()
        if self.on_fallback is not None:
            self.on_fallback(site)
        fb = self._fb_contrib.get(int(ncol))
        if fb is None:
            with jax.experimental.enable_x64():
                fb = tuple(
                    type(part)(*[
                        jnp.reshape(a, (a.shape[0] * a.shape[1], 1)
                                    + a.shape[2:]) for a in part])
                    for part in self._contrib[int(ncol)])
            self._fb_contrib[int(ncol)] = fb
        with jax.experimental.enable_x64():
            res = np.asarray(predict_contrib_scan_fallback(
                fb, jnp.asarray(chunk)))
        _recompile.note_dispatch(
            "predict_contrib_fallback", bucket,
            predict_contrib_scan_fallback._cache_size())
        return res

    def _contrib_host_scan(self, X: np.ndarray, ncol: int,
                           exc: Exception) -> np.ndarray:
        """The last-resort net under :meth:`predict_contrib`: the host
        per-tree TreeSHAP recursion on the f32-cast raw rows (routing
        matches the device decide by the floored-threshold contract).
        Binned rows carry bin CODES, not feature values — the host scan
        cannot route them, so a binned double-failure re-raises (the
        caller's raw-path booster fallback still applies)."""
        if self.kind != "raw":
            raise exc
        from ..resilience import note_fallback
        from ..utils.log import Log
        site = ("predict_contrib@%s" % self.owner if self.owner
                else "predict_contrib")
        Log.warning("device pred_contrib failed beyond the degraded "
                    "program (%s: %s); serving via the host TreeSHAP scan",
                    type(exc).__name__, exc)
        note_fallback(site, reason="%s: %s" % (type(exc).__name__, exc),
                      rows=int(len(X)),
                      **({"model": self.owner} if self.owner else {}))
        tele = _telemetry_active()
        if tele is not None:
            tele.counter("contrib_fallbacks").inc()
        if self.on_fallback is not None:
            self.on_fallback(site)
        out = np.zeros((len(X), int(ncol)), dtype=np.float64)
        for tree in self._trees:
            out += tree.predict_contrib(X, int(ncol))
        return out

    # ---- degraded mode (resilience): per-tree scan fallback ----

    def _fallback_ens(self):
        """g=1 re-blocking of the degraded path, built lazily on the first
        failure (a healthy predictor never pays for it) by RESHAPING the
        stacked ensemble: [T/G, G, ...] -> [T_pad, 1, ...].  Pad trees stay
        dead (path_len -1 never matches, leaf values 0) and trail the real
        ones, so scores, early-stop check positions and the leading
        ``n_trees`` leaf columns are unchanged — same bit-exactness
        argument as any other blocking."""
        if self._fb_ens is None:
            self._fb_ens = type(self.ens)(*[
                jnp.reshape(a, (a.shape[0] * a.shape[1], 1) + a.shape[2:])
                for a in self.ens])
        return self._fb_ens

    def _predict_degraded(self, rows, bucket: int, exc: Exception,
                          early_stop_margin: float, round_period: int,
                          want_leaf: bool):
        """Serve the chunk through the per-tree scan after the blocked
        dispatch failed: counted (``resilience.note_fallback`` +
        ``predict_fallbacks`` telemetry counter), warned once per
        predictor, bit-exact with the blocked result."""
        from ..resilience import note_fallback
        from ..utils.log import Log
        if not self._fb_warned:
            self._fb_warned = True
            Log.warning("fused predict failed for bucket %d (%s: %s); "
                        "serving DEGRADED via the per-tree scan path",
                        bucket, type(exc).__name__, exc)
        # serving runs carry the owning model in the site key so fallback
        # counts surface per model in the registry stats + summary
        site = ("%s@%s" % (self._site, self.owner) if self.owner
                else self._site)
        note_fallback(site, reason="%s: %s" % (type(exc).__name__, exc),
                      bucket=int(bucket),
                      **({"model": self.owner} if self.owner else {}))
        if self.on_fallback is not None:
            self.on_fallback(site)
        out = predict_scan_fallback(
            self._fallback_ens(), rows,
            early_stop_margin=float(early_stop_margin),
            round_period=int(round_period), want_leaf=want_leaf)
        # the fallback's own compiles are recompiles too — a steady-state
        # degraded loop must also read zero after its first bucket compile
        # (both tiers share the fallback jit cache; watch= keys it once)
        _recompile.note_dispatch(
            "predict_fallback" if self.precision == "exact"
            else "predict_fallback_bf16", bucket,
            predict_scan_fallback._cache_size(), watch="predict_fallback")
        return out
