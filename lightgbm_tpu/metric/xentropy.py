"""Cross-entropy metrics (src/metric/xentropy_metric.hpp): cross_entropy,
cross_entropy_lambda, kullback_leibler."""
from __future__ import annotations

import numpy as np

from .metric import Metric

_LOG_EPS = 1.0e-12


def _xent_loss(label, prob):
    a = label * np.log(np.maximum(prob, _LOG_EPS))
    b = (1.0 - label) * np.log(np.maximum(1.0 - prob, _LOG_EPS))
    return -(a + b)


class CrossEntropyMetric(Metric):
    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.names = ["cross_entropy"]

    def eval(self, score, objective=None):
        s = np.asarray(score, dtype=np.float64).reshape(-1)
        prob = 1.0 / (1.0 + np.exp(-s))
        return [self._avg(_xent_loss(self.label, prob))]


class CrossEntropyLambdaMetric(Metric):
    """Loss under the lambda parameterization: hhat = log1p(exp(f)),
    prob = 1 - exp(-w*hhat) (xentropy_metric.hpp xentlambda)."""

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.names = ["cross_entropy_lambda"]

    def eval(self, score, objective=None):
        s = np.asarray(score, dtype=np.float64).reshape(-1)
        w = np.ones_like(s) if self.weights is None else self.weights
        hhat = np.log1p(np.exp(s))
        prob = 1.0 - np.exp(-w * hhat)
        loss = _xent_loss(self.label, prob)
        return [float(loss.sum() / self.num_data)]


class KullbackLeiblerDivergence(Metric):
    """KL(label || prob) = xent(label, prob) - H(label)."""

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.names = ["kullback_leibler"]
        p = self.label
        self.label_entropy = _xent_loss(p, np.clip(p, _LOG_EPS, 1 - _LOG_EPS))

    def eval(self, score, objective=None):
        s = np.asarray(score, dtype=np.float64).reshape(-1)
        prob = 1.0 / (1.0 + np.exp(-s))
        return [self._avg(_xent_loss(self.label, prob) - self.label_entropy)]
